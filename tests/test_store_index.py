"""The indexed artifact store (runner/store_index.py).

The contract under test: the sqlite run index is an ACCELERATOR, never
a second source of truth — every reader (the /aggregate dashboard, the
tel subcommands) must produce bit-identical output whether it replays
index rows or walks the tree, incremental writes must land the same
rows a full rebuild derives, `store index` must detect tree/index
drift, and retention compaction must be lossless for every summary
surface while never touching a failed run's artifacts.
"""

import json
import os
import random
import shutil
import types

import pytest

from jepsen_etcd_tpu import serve, tel_cli
from jepsen_etcd_tpu.runner import store_index, telemetry
from jepsen_etcd_tpu.runner.store import failure_signature, rotate_store


@pytest.fixture(autouse=True)
def _fresh_caches():
    """Per-process fold/render caches are keyed by abspath; tmp_path
    makes keys unique, but clear anyway so no test leaks cache state."""
    yield
    serve._AGG_CACHE.clear()
    store_index._FOLDS.clear()


def run_results(valid=True, count=100, frontier=3, rungs=2, spills=0,
                waves=4, buckets=None, gen_rate=1200.0):
    tel = {"phases": {"generate": 0.4, "check": 0.2},
           "counters": {"generate.ops_per_s": gen_rate,
                        "wgl.max-frontier": frontier,
                        "wgl.rungs": rungs,
                        "wgl.host-spill": spills,
                        "wgl.waves": waves},
           "hists": {}}
    if buckets:
        tel["hists"]["wgl.rung_waves"] = {
            "count": sum(buckets.values()),
            "buckets": {str(b): c for b, c in buckets.items()}}
    return {"valid?": valid, "stats": {"count": count},
            "workload": {"valid?": valid}, "telemetry": tel}


def mk_run(base, tname, rid, results=None, history=True, shrink=None,
           tel_lines=None):
    d = os.path.join(str(base), tname, rid)
    os.makedirs(d)
    if results is None:
        results = run_results()
    with open(os.path.join(d, "results.json"), "w") as f:
        json.dump(results, f)
    test = {"name": tname, "workload": "register",
            "nemesis_spec": ["kill"], "db_mode": "sim",
            "time_limit": 5, "seed": int(rid)}
    with open(os.path.join(d, "test.json"), "w") as f:
        json.dump(test, f)
    if history:
        with open(os.path.join(d, "history.jsonl"), "w") as f:
            f.write('{"type": "invoke", "f": "write", "value": 1}\n')
    if shrink is not None:
        with open(os.path.join(d, "shrink.json"), "w") as f:
            json.dump(shrink, f)
    if tel_lines is not None:
        with open(os.path.join(d, "telemetry.jsonl"), "w") as f:
            f.write("".join(json.dumps(r) + "\n" for r in tel_lines))
    return d


SHRINK = {"signature": "workload=False", "workload": "register",
          "original_windows": 4, "windows": 1, "nemesis_ops": 2,
          "rounds": 3, "executions": 9,
          "repro": {"seed": 2, "nem_schedule": [[0.1, 0.3]]}}


def mk_campaign(base, name, cid):
    cdir = os.path.join(str(base), name, cid)
    os.makedirs(cdir)
    rows = [{"status": "done", "trace": "tA", "service_shipped": 2,
             "service_queue_wait_s": 0.5, "gen_ops_per_s": 900.0,
             "dispatches": 3, "check_s": 0.2,
             "dir": os.path.join("..", "..", "reg", "00001")},
            {"status": "done", "trace": "tB", "service_shipped": 1,
             "service_queue_wait_s": 0.25, "gen_ops_per_s": 1100.0,
             "dispatches": 1, "check_s": 0.1},
            {"status": "error", "host": "h2"}]
    summary = {"name": name, "trace": "camp-1", "count": 3, "pool": 2,
               "valid?": False, "wall_s": 4.5, "runs": rows,
               "service": {"counters": {"service.submitted": 3,
                                        "service.queue_wait_s": 0.75,
                                        "wgl.dispatches": 4}}}
    with open(os.path.join(cdir, "campaign.json"), "w") as f:
        json.dump(summary, f)
    ticks = [{"kind": "span", "name": "service.tick", "dur_s": 0.01,
              "attrs": {"runs": ["tA"]}},
             {"kind": "span", "name": "service.tick", "dur_s": 0.02,
              "attrs": {"runs": ["tB"]}}]
    with open(os.path.join(cdir, "service.jsonl"), "w") as f:
        f.write("".join(json.dumps(r) + "\n" for r in ticks))
    return cdir


def mk_guided(base, name, gid):
    gdir = os.path.join(str(base), name, gid)
    os.makedirs(gdir)
    summary = {"kind": "guided", "name": name, "budget": 8, "runs": 6,
               "generations": 2, "master_seed": 7,
               "signatures": {"workload=False": 3},
               "first_failure_run": 3, "wall_s": 1.2,
               "envelope": {"frontier": 3},
               "corpus": [{"opts": {"workload": "register",
                                    "nemesis": ["kill"], "seed": 9},
                           "seed": 9, "run": 3, "score": 4,
                           "signature": "workload=False",
                           "vector": {"frontier": 3}}],
               "minimized": [dict(SHRINK, run=3)]}
    with open(os.path.join(gdir, "guided.json"), "w") as f:
        json.dump(summary, f)
    # the guided dir is its own index base: runs nest one level deeper
    mk_run(gdir, "g-reg", "00001",
           results=run_results(valid=False, count=40, frontier=5,
                               buckets={3: 2, 24: 1}),
           shrink=SHRINK)
    mk_run(gdir, "g-reg", "00002",
           results=run_results(count=44, buckets={2: 6}))
    return gdir


TEL_A = [{"kind": "span", "name": "phase:check",
          "dur_s": 0.012345678901234, "trace": "tA"},
         {"kind": "span", "name": "phase:check", "dur_s": 0.031},
         {"kind": "span", "name": "wgl.check_packed", "dur_s": 0.002},
         {"kind": "counter", "name": "wgl.rungs", "value": 3}]
TEL_B = [{"kind": "span", "name": "phase:check", "dur_s": 0.05,
          "trace": "tB"},
         {"kind": "hist", "name": "wgl.rung_waves", "count": 2,
          "sum": 9.0, "min": 3.0, "max": 6.0,
          "buckets": {"2": 1, "3": 1}},
         {"kind": "counter", "name": "wgl.rungs", "value": 4}]


@pytest.fixture
def store(tmp_path):
    base = str(tmp_path / "store")
    mk_run(base, "reg", "00001",
           results=run_results(count=120, buckets={3: 4, 10: 1}),
           tel_lines=TEL_A)
    mk_run(base, "reg", "00002",
           results=run_results(valid=False, count=80, frontier=6,
                               spills=1, buckets={10: 2}),
           shrink=SHRINK, tel_lines=TEL_B)
    mk_run(base, "kill", "00001",
           results=run_results(count=60, gen_rate=800.0))
    mk_campaign(base, "camp", "001")
    mk_guided(base, "fuzz", "001")
    return base


def _serve_rows(base):
    return {"runs": serve._run_rows(base),
            "campaigns": serve._campaign_rows(base),
            "guided": serve._guided_rows(base),
            "shrink": serve._shrink_rows(base)}


# -- rebuild / incremental / verify ------------------------------------------


def test_rebuild_replays_walk_rows_bit_identically(store):
    walk = _serve_rows(store)  # no index yet: pure tree walk
    assert len(walk["runs"]) == 3
    assert len(walk["shrink"]) == 2  # base run + guided-subtree run
    out = store_index.rebuild(store)
    assert out["ok"] and out["runs"] == 3 and out["campaigns"] == 1
    assert out["guided"] == 1 and out["shrink"] == 1
    assert "fuzz/001" in out["sub_indexes"]
    assert store_index.has_index(store)
    assert store_index.has_index(os.path.join(store, "fuzz", "001"))
    assert store_index.fold(store) is not None
    assert _serve_rows(store) == walk


def test_incremental_writes_match_rebuild(tmp_path):
    base = str(tmp_path / "inc")
    mk_run(base, "reg", "00001")
    # first hook into an unindexed tree backfills before upserting —
    # a fresh index must never start as a partial one
    mk_run(base, "reg", "00002")
    assert store_index.record_run(os.path.join(base, "reg", "00002"))
    f = store_index.fold(base)
    assert store_index.kind_dirs(f, "run") == \
        [os.path.join("reg", "00001"), os.path.join("reg", "00002")]

    rdir = mk_run(base, "kill", "00001",
                  results=run_results(valid=False), shrink=SHRINK)
    assert store_index.record_run(rdir)
    assert store_index.record_shrink(rdir)
    cdir = mk_campaign(base, "camp", "001")
    assert store_index.record_campaign(cdir)
    gdir = mk_guided(base, "fz", "001")
    assert store_index.record_guided(gdir)

    incremental = store_index.fold(base).rows.copy()
    store_index.rebuild(base)
    assert store_index.fold(base).rows == incremental


def test_verify_flags_missing_and_stale_rows(store):
    store_index.rebuild(store)
    v = store_index.verify(store)
    assert v["ok"] and v["tree_runs"] == v["index_runs"] == 3
    assert v["fingerprint"]["tree"] == v["fingerprint"]["index"]

    mk_run(store, "late", "00001")
    v = store_index.verify(store)
    assert not v["ok"]
    assert v["missing"] == [os.path.join("late", "00001")]
    store_index.record_run(os.path.join(store, "late", "00001"))
    assert store_index.verify(store)["ok"]

    shutil.rmtree(os.path.join(store, "late"))
    v = store_index.verify(store)
    assert not v["ok"] and v["stale"] == [os.path.join("late", "00001")]
    store_index.mark_deleted(store, [os.path.join("late", "00001")])
    assert store_index.verify(store)["ok"]


def test_rotation_tombstones_index_rows(tmp_path):
    base = str(tmp_path / "rot")
    for i in range(1, 4):
        d = mk_run(base, "reg", f"{i:05d}")
        with open(os.path.join(d, "history.jsonl"), "w") as f:
            f.write("x" * 4096)
        os.utime(d, (1000.0 * i, 1000.0 * i))
    store_index.rebuild(base)
    keep = os.path.join(base, "reg", "00003")
    removed = rotate_store(base, keep_dir=keep, max_bytes=6000)
    assert removed  # the oldest run(s) went
    rows = serve._run_rows(base)
    dirs = {r["dir"] for r in rows}
    assert os.path.join("reg", "00003") in dirs
    for rd in removed:
        assert os.path.relpath(rd, base) not in dirs
    assert store_index.verify(base)["ok"]


def test_live_registration_and_snapshot(tmp_path):
    base = str(tmp_path / "live")
    mk_run(base, "reg", "00001")
    cdir = os.path.join(base, "camp", "001")
    os.makedirs(cdir)
    with open(os.path.join(cdir, "live.json"), "w") as f:
        json.dump({"phase": "running", "done": 1}, f)
    assert store_index.note_live(cdir)
    assert store_index.live_candidates(base) == \
        [os.path.join("camp", "001")]
    snap, _mtime, rel = serve._live_snapshot(base)
    assert snap == {"phase": "running", "done": 1}
    assert rel == os.path.join("camp", "001")
    # folding the campaign tombstones the live row; the campaign row
    # keeps the dir on the SSE candidate list
    mk_campaign(base, "camp", "002")  # distinct dir, still live-less
    cdir2 = mk_campaign(base, "camp2", "001")
    store_index.record_campaign(cdir2)
    f = store_index.fold(base)
    assert ("live", os.path.join("camp", "001")) in f.rows
    assert ("campaign", os.path.join("camp2", "001")) in f.rows


# -- /aggregate serving -------------------------------------------------------


def test_aggregate_pagination_windows_and_clamps(tmp_path):
    base = str(tmp_path / "pg")
    for i in range(12):
        mk_run(base, f"t{i % 3}", f"{i:05d}",
               results=run_results(valid=i % 4 != 0))
    store_index.rebuild(base)
    p1 = serve.aggregate_html(base, page=1, per=5)
    assert "12 runs" in p1 and "rows 1–5 of 12" in p1
    assert 'href="/aggregate?page=2&amp;per=5"' in p1
    p3 = serve.aggregate_html(base, page=3, per=5)
    assert "rows 11–12 of 12" in p3 and "page 3/3" in p3
    # out-of-range and junk query args clamp instead of erroring
    assert "rows 11–12 of 12" in serve.aggregate_html(base, page="99",
                                                      per="5")
    assert "rows 1–5 of 12" in serve.aggregate_html(base, page="0",
                                                    per="5")
    one = serve.aggregate_html(base, page="junk", per="junk")
    assert "12 runs" in one and "rows " not in one  # single page
    assert serve._page_window(0, 1, 5) == (0, 0, 1, 1, 5)
    assert serve._page_window(12, 2, 10 ** 9)[4] == serve._MAX_PER


def test_aggregate_render_cache_invalidates_on_index_writes(tmp_path):
    base = str(tmp_path / "cache")
    for i in range(4):
        mk_run(base, "reg", f"{i:05d}")
    store_index.rebuild(base)
    p1 = serve.aggregate_html(base, page=1, per=2)
    assert serve.aggregate_html(base, page=1, per=2) is p1  # cache hit
    store_index.record_run(mk_run(base, "reg", "00099"))
    p2 = serve.aggregate_html(base, page=1, per=2)
    assert p2 is not p1 and "5 runs" in p2


# -- index-backed tel, bit-identical to the walks -----------------------------


def _capture(capsys, fn, *args, **kw):
    rc = fn(*args, **kw)
    out = capsys.readouterr().out
    assert out
    return rc, out


@pytest.mark.parametrize("as_json", [False, True])
def test_tel_coverage_index_matches_walk(store, as_json, capsys):
    store_index.rebuild(store)
    rc_i, via_index = _capture(capsys, tel_cli.cmd_coverage, [store],
                               as_json, use_index=True)
    rc_w, via_walk = _capture(capsys, tel_cli.cmd_coverage, [store],
                              as_json, use_index=False)
    assert rc_i == rc_w == 0
    assert via_index == via_walk
    # the guided subtree's runs are in the fold's answer (5 = 3 base
    # runs + 2 nested under fuzz/001)
    assert "workload=False" in via_index
    if as_json:
        got = json.loads(via_index)
        assert got["aggregate"]["count"] == 5
        assert sum("g-reg" in r["dir"] for r in got["runs"]) == 2
    else:
        assert "coverage over 5 run(s)" in via_index


@pytest.mark.parametrize("as_json", [False, True])
def test_tel_ledger_index_matches_walk(store, as_json, capsys):
    store_index.rebuild(store)
    cdir = os.path.join(store, "camp", "001")
    assert store_index.ledger_ticks(cdir) is not None
    rc_i, via_index = _capture(capsys, tel_cli.cmd_ledger, [cdir],
                               as_json, use_index=True)
    rc_w, via_walk = _capture(capsys, tel_cli.cmd_ledger, [cdir],
                              as_json, use_index=False)
    assert rc_i == rc_w == 0
    assert via_index == via_walk
    # a rewritten service.jsonl invalidates the cached trace join
    with open(os.path.join(cdir, "service.jsonl"), "a") as f:
        f.write(json.dumps({"kind": "span", "name": "service.tick",
                            "dur_s": 0.01, "attrs": {"runs": []}})
                + "\n")
    assert store_index.ledger_ticks(cdir) is None
    rc, _ = _capture(capsys, tel_cli.cmd_ledger, [cdir], as_json,
                     use_index=True)
    assert rc == 0  # falls back to the rescan, never serves stale


@pytest.mark.parametrize("as_json", [False, True])
def test_tel_diff_index_matches_walk(store, as_json, capsys):
    store_index.rebuild(store)
    a = os.path.join(store, "reg", "00001")
    b = os.path.join(store, "reg", "00002")
    _, cold = _capture(capsys, tel_cli.cmd_diff, [a, b], as_json,
                       use_index=True)
    _, cached = _capture(capsys, tel_cli.cmd_diff, [a, b], as_json,
                         use_index=True)
    _, walk = _capture(capsys, tel_cli.cmd_diff, [a, b], as_json,
                       use_index=False)
    assert cold == cached == walk
    con = store_index._connect(store)
    try:
        n = con.execute("SELECT COUNT(*) FROM tel_cache").fetchone()[0]
    finally:
        con.close()
    assert n == 2  # both operands' profiles are cached


@pytest.mark.parametrize("as_json", [False, True])
def test_tel_corpus_index_matches_walk(store, as_json, capsys):
    store_index.rebuild(store)
    rc_i, via_index = _capture(capsys, tel_cli.cmd_corpus, [store],
                               as_json, use_index=True)
    rc_w, via_walk = _capture(capsys, tel_cli.cmd_corpus, [store],
                              as_json, use_index=False)
    assert rc_i == rc_w == 0
    assert via_index == via_walk


def test_tel_profile_cache_serves_exact_profiles(store):
    store_index.rebuild(store)
    path = os.path.join(store, "reg", "00001", "telemetry.jsonl")
    calls = []

    def scan_fn(paths):
        calls.append(list(paths))
        return tel_cli.scan(paths)

    def flat(prof):
        return {"records": prof["records"], "skipped": prof["skipped"],
                "counters": prof["counters"],
                "traces": sorted(prof["traces"]),
                "spans": {n: store_index._hist_exact(h)
                          for n, h in prof["spans"].items()},
                "hists": {n: store_index._hist_exact(h)
                          for n, h in prof["hists"].items()}}

    p1 = store_index.tel_profile(path, scan_fn)
    p2 = store_index.tel_profile(path, scan_fn)
    assert len(calls) == 1  # second read served from the cache
    assert flat(p1) == flat(p2)
    # a rewrite changes the fingerprint: rescan, never stale
    with open(path, "a") as f:
        f.write(json.dumps({"kind": "counter", "name": "wgl.rungs",
                            "value": 1}) + "\n")
    p3 = store_index.tel_profile(path, scan_fn)
    assert len(calls) == 2
    assert p3["counters"]["wgl.rungs"] == \
        p1["counters"]["wgl.rungs"] + 1


# -- retention compaction -----------------------------------------------------


def _tree_bytes(d):
    out = {}
    for root, dirs, files in os.walk(d):
        dirs.sort()
        for fn in sorted(files):
            p = os.path.join(root, fn)
            with open(p, "rb") as fh:
                out[os.path.relpath(p, d)] = fh.read()
    return out


def test_compaction_is_lossless_fuzz(tmp_path, capsys):
    rng = random.Random(1234)
    for case in range(4):
        base = str(tmp_path / f"s{case}")
        n = rng.randrange(8, 18)
        failing = set()
        for i in range(n):
            valid = rng.random() >= 0.35
            if not valid:
                failing.add(os.path.join(f"t{i % 3}", f"{i:05d}"))
            buckets = {rng.randrange(1, 30): rng.randrange(1, 9)
                       for _ in range(rng.randrange(0, 4))}
            d = mk_run(base, f"t{i % 3}", f"{i:05d}",
                       results=run_results(
                           valid=valid, count=50 + i,
                           frontier=rng.randrange(1, 9),
                           rungs=rng.randrange(5),
                           spills=rng.randrange(2),
                           waves=rng.randrange(1, 6),
                           buckets=buckets),
                       shrink=SHRINK if (not valid and
                                         rng.random() < 0.5) else None)
            os.utime(d, (1000.0 + i, 1000.0 + i))
        store_index.rebuild(base)
        keep = rng.randrange(1, 5)

        serve._AGG_CACHE.clear()
        html_pre = serve.aggregate_html(base)
        rows_pre = serve._run_rows(base)
        cov_pre = tel_cli.coverage(base, use_index=True)
        failed_pre = {rel: _tree_bytes(os.path.join(base, rel))
                      for rel in failing}

        out = store_index.compact(base, keep=keep)
        assert out["ok"] and not out["dry_run"]
        assert not set(out["compacted_dirs"]) & failing

        # every summary surface replays identically after compaction
        serve._AGG_CACHE.clear()
        assert serve.aggregate_html(base) == html_pre
        assert serve._run_rows(base) == rows_pre
        assert tel_cli.coverage(base, use_index=True) == cov_pre
        assert tel_cli.coverage(base, use_index=False) == cov_pre

        # failed runs' artifacts are byte-untouched, never deleted
        for rel in sorted(failing):
            assert _tree_bytes(os.path.join(base, rel)) == \
                failed_pre[rel], rel
        # demoted passing runs keep ONLY the summary files
        for rel in out["compacted_dirs"]:
            left = set(os.listdir(os.path.join(base, rel)))
            assert left <= set(store_index.COMPACT_KEEP)
            assert "results.json" in left and "test.json" in left
        # candidate accounting: everything older than the spared tail
        # was either demoted or skipped as a failure
        assert out["compacted"] + out["skipped_failures"] == \
            max(0, n - keep)
        assert store_index.verify(base)["ok"]


def test_compact_dry_run_and_counters(tmp_path):
    base = str(tmp_path / "c")
    for i in range(6):
        d = mk_run(base, "reg", f"{i:05d}",
                   results=run_results(valid=i != 0))
        os.utime(d, (1000.0 + i, 1000.0 + i))
    store_index.rebuild(base)
    tel = telemetry.Telemetry(None)
    telemetry.set_current(tel)
    try:
        dry = store_index.compact(base, keep=2, dry_run=True)
        assert dry["dry_run"] and dry["compacted"] == 3
        assert dry["skipped_failures"] == 1  # run 0 failed, spared
        for i in range(6):  # nothing actually removed
            assert os.path.exists(os.path.join(
                base, "reg", f"{i:05d}", "history.jsonl"))
        out = store_index.compact(base, keep=2)
        assert out["compacted"] == 3 and out["skipped_failures"] == 1
    finally:
        telemetry.set_current(telemetry.NULL)
    ctr = tel.summary()["counters"]
    tel.close()
    assert ctr["store.compacted"] == 6  # dry + real pass both count
    assert ctr["store.compact_skipped_failures"] == 2
    # the demoted runs are now invisible to all_runs but still served
    assert len(serve._run_rows(base)) == 6
    v = store_index.verify(base)
    assert v["ok"] and v["compacted"] == 3 and v["tree_runs"] == 3


def test_new_counters_are_registered():
    reg = telemetry.REGISTRY["counters"]
    for name in ("store.index_rows", "store.index_writes",
                 "store.compacted", "store.compact_skipped_failures",
                 "guided.corpus_retired"):
        assert name in reg, name


# -- the `store` CLI ----------------------------------------------------------


def _cli(capsys, **kw):
    ns = types.SimpleNamespace(action="index", store=None,
                               rebuild=False, keep=32, dry_run=False)
    ns.__dict__.update(kw)
    rc = store_index.cli_store(ns)
    return rc, json.loads(capsys.readouterr().out)


def test_store_cli_index_and_compact(store, capsys):
    rc, out = _cli(capsys, store=store, rebuild=True)
    assert rc == 0 and out["ok"] and out["rows"] == 6
    assert out["counters"]["store.index_rows"] >= 6
    rc, out = _cli(capsys, store=store)  # verify mode
    assert rc == 0 and out["ok"] and out["index_runs"] == 3
    # keep=1 spares the newest run; of the two older ones the failing
    # reg/00002 is protected, so exactly one passing run demotes
    rc, out = _cli(capsys, store=store, action="compact", keep=1)
    assert rc == 0 and out["ok"] and out["compacted"] == 1
    assert out["skipped_failures"] == 1
    assert out["counters"]["store.compacted"] == 1
    # drift makes the verify exit nonzero (the CI hook contract)
    shutil.rmtree(os.path.join(store, "kill"))
    rc, out = _cli(capsys, store=store)
    assert rc == 1 and not out["ok"]


def test_store_cli_dispatches_through_main(store, capsys):
    from jepsen_etcd_tpu.cli import main
    assert main(["store", "index", "--rebuild", "--store", store]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["ok"] and out["rows"] == 6


def test_failure_signature_is_canonical():
    res = {"valid?": False,
           "workload": {"valid?": False},
           "staleness": {"valid?": "unknown"},
           "perf": {"valid?": True},
           "stats": {"count": 3}}
    sig = failure_signature(res)
    assert sig == "staleness=unknown, workload=False"
    assert serve._failure_signature(res) == sig
    from jepsen_etcd_tpu.runner.shrink import _signature
    assert _signature(res) == sig
