"""Same-seed columnar/dict equivalence fuzz (r6 tentpole guard).

The interpreter records every history twice: the dict op stream (the
serialization- and replay-compatible representation) and the typed SoA
columns (core/history.py OpColumns) the hot checker paths consume. This
suite pins the contract between the two:

- materializing the columns back to ops is *bit-identical* to the dict
  stream — index, time, process, type, f, value, and every extra key —
  for every workload, with and without nemeses;
- the composed checker reaches the same verdicts whether it is handed
  the dual-backed recorded history (columnar fast paths engaged) or a
  dict-only copy (reference paths);
- the flagship columnar pipeline — ``split_by_key`` into the batched
  register packer — runs without a single dict materialization
  (``History.dict_materializations`` stays 0).
"""

import json

import pytest

from jepsen_etcd_tpu.checkers.core import Noop
from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.runner.test_runner import run_test

#: one config per workload; nemesis mixes mirror the cross-run battery
#: at small time limits so the whole file stays tier-1-fast
CONFIGS = {
    "register-nemesis": dict(workload="register",
                             nodes=["n1", "n2", "n3"],
                             time_limit=5, rate=200, seed=11,
                             nemesis=["kill", "partition"],
                             nemesis_interval=2),
    "set-nemesis": dict(workload="set", time_limit=4, rate=200, seed=19,
                        nemesis=["pause", "clock"], nemesis_interval=2),
    "append-nemesis": dict(workload="append", nodes=["n1", "n2", "n3"],
                           time_limit=4, rate=150, seed=5,
                           nemesis=["partition"], nemesis_interval=2),
    "watch": dict(workload="watch", time_limit=4, rate=150, seed=9),
    "lock": dict(workload="lock", nodes=["n1", "n2", "n3"],
                 time_limit=5, rate=100, seed=13, nemesis=["kill"],
                 nemesis_interval=2),
    "wr": dict(workload="wr", nodes=["n1", "n2", "n3"],
               time_limit=4, rate=200, seed=21),
}


def _record(tmp_path, name):
    """Run the config's sim; returns (test, composed_checker, history).

    The run itself uses a Noop checker — the composed checker is
    exercised explicitly on both representations by the test."""
    cfg = dict(CONFIGS[name])
    cfg["store_base"] = str(tmp_path)
    cfg["no_telemetry"] = True
    test = etcd_test(cfg)
    checker = test["checker"]
    test["checker"] = Noop()
    out = run_test(test)
    return test, checker, out["history"]


def _strip(result) -> str:
    return json.dumps(result, sort_keys=True, default=repr)


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_columns_equivalent_and_verdicts_agree(tmp_path, name):
    test, checker, h = _record(tmp_path, name)
    cols = h.columns
    assert cols is not None, "recorded history lost its columns"
    assert len(cols) == len(h)

    # 1) column materialization is bit-identical to the dict stream
    back = History.from_columns(cols).ops
    assert len(back) == len(h.ops)
    for a, b in zip(h.ops, back):
        assert dict(a) == dict(b), (dict(a), dict(b))

    # 2) composed checker: columnar fast paths vs dict-only reference
    res_cols = checker.check(test, h)
    h_dict = History(list(h.ops))          # no columns attached
    assert h_dict.columns is None
    res_dict = checker.check(test, h_dict)
    assert _strip(res_cols) == _strip(res_dict)
    assert res_cols["valid?"] == res_dict["valid?"]


#: chunk sizes swept by the streaming fuzz; None = whole history in one
#: flush (chunk_ops larger than the run)
CHUNK_SIZES = (1, 64, 4096, None)


def _replay_stream(test, h, chunk_ops):
    """Re-feed the recorded op stream through a fresh ColumnsBuilder +
    StreamFeed at the given chunk size — the identical column stream the
    live interpreter would have produced, so ONE sim run fuzzes every
    chunk size. Returns the validated hint map."""
    from jepsen_etcd_tpu.core.history import ColumnsBuilder
    from jepsen_etcd_tpu.runner.stream import StreamFeed

    carrier = {"workload": test.get("workload")}
    feed = StreamFeed(carrier, chunk_ops=chunk_ops or (len(h) + 1))
    builder = ColumnsBuilder()
    feed.attach(builder)
    for op in h.ops:
        builder.append(op)
        feed.on_record()
    hints = feed.finish(h)
    assert feed.error is None
    assert hints["stats"]["rows"] == len(h)
    if chunk_ops == 1:
        assert hints["stats"]["chunks"] == len(h)
    elif chunk_ops is None:
        assert hints["stats"]["chunks"] == 1
    return hints


def _assert_artifact_equal(a, b, path="artifact"):
    """Deep equality over the hint artifacts (nested dicts / tuples /
    dataclass packs / numpy arrays) — json-dumps would silently
    truncate large arrays."""
    import dataclasses
    import numpy as np

    if isinstance(a, dict):
        assert isinstance(b, dict) and set(a) == set(b), path
        for k in a:
            _assert_artifact_equal(a[k], b[k], f"{path}[{k!r}]")
    elif isinstance(a, (list, tuple)):
        assert type(a) is type(b) and len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_artifact_equal(x, y, f"{path}[{i}]")
    elif isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        assert np.array_equal(a, b), path
    elif dataclasses.is_dataclass(a):
        assert type(a) is type(b), path
        for fld in dataclasses.fields(type(a)):
            _assert_artifact_equal(getattr(a, fld.name),
                                   getattr(b, fld.name),
                                   f"{path}.{fld.name}")
    else:
        assert a == b, (path, a, b)


@pytest.mark.parametrize("name", ["register-nemesis", "set-nemesis"])
def test_streaming_verdicts_bit_identical_across_chunk_sizes(
        tmp_path, name):
    """ISSUE 8 fuzz: for every chunk size, the checker handed streamed
    hints reaches a verdict BIT-identical to the post-hoc pass, and the
    hint artifacts themselves are deterministic across chunk sizes
    (chunk boundaries choose pause points, never results)."""
    hint_key = ("register_packs" if name.startswith("register")
                else "set_scan")
    test, checker, h = _record(tmp_path, name)
    test.pop("_stream", None)
    posthoc = _strip(checker.check(test, h))
    artifacts = {}
    for cs in CHUNK_SIZES:
        hints = _replay_stream(test, h, cs)
        assert hint_key in hints, (cs, sorted(hints))
        test["_stream"] = hints
        try:
            streamed = _strip(checker.check(test, h))
        finally:
            test.pop("_stream", None)
        assert streamed == posthoc, f"verdict diverged at chunk={cs}"
        artifacts[cs] = hints[hint_key]
    base_cs = CHUNK_SIZES[0]
    for cs in CHUNK_SIZES[1:]:
        _assert_artifact_equal(artifacts[cs], artifacts[base_cs],
                               f"chunk={cs} vs chunk={base_cs}")


def test_streaming_register_pipeline_no_dict_materialization(tmp_path):
    """ISSUE 8 tier-1 guard: the streaming register path — chunked
    PackStream feeding plus hint validation — performs zero
    History.dict_materializations, and its packs are the batched
    packer's packs bit for bit. (The small-key DFS fallback materializes
    dicts by design on BOTH streamed and post-hoc runs; the streaming
    contract covers the feed/pack/hint pipeline.)"""
    from jepsen_etcd_tpu.checkers.core import stream_hint
    from jepsen_etcd_tpu.core.history import ColumnsBuilder
    from jepsen_etcd_tpu.ops import wgl

    cfg = dict(workload="register", nodes=["n1", "n2", "n3"],
               time_limit=20, rate=0, ops_per_key=60, seed=17,
               snapshot_count=100_000, store_base=str(tmp_path),
               no_telemetry=True)
    test = etcd_test(cfg)
    test["checker"] = Noop()
    h = run_test(test)["history"]
    assert h.columns is not None

    History.dict_materializations = 0
    ps = wgl.PackStream()
    builder = ColumnsBuilder()
    for i, op in enumerate(h.ops, 1):   # dict ops already exist: the
        builder.append(op)              # replayed feed sees the same
        if i % 256 == 0:                # column chunks the live
            ps.feed(builder.take_chunk())  # interpreter drains
    ps.feed(builder.take_chunk())
    packs = ps.finish()
    assert ps.ok and packs is not None
    assert ps.n_rows == len(h)

    # hint validation on a column-only history is dict-free too
    h2 = History.from_columns(h.columns)
    test["_stream"] = {"stats": {}, "register_packs": (packs, ps.n_rows)}
    assert stream_hint(test, h2, "register_packs") is packs
    assert History.dict_materializations == 0, \
        "streaming register path materialized dict ops"

    ref = wgl.pack_register_histories_batched(h2.split_by_key())
    assert set(packs) == set(ref)
    for k in ref:
        wgl.ensure_frames(packs[k])
        wgl.ensure_frames(ref[k])
    _assert_artifact_equal(packs, ref, "streamed packs vs batched")


def test_columnar_register_pipeline_no_dict_materialization(tmp_path):
    """Tier-1 regression guard (r6 acceptance): the columnar checker
    path — split_by_key into the batched SoA register packer — must not
    round-trip through dict ops at all."""
    from jepsen_etcd_tpu.ops import wgl

    cfg = dict(workload="register", nodes=["n1", "n2", "n3"],
               time_limit=20, rate=0, ops_per_key=60, seed=17,
               snapshot_count=100_000, store_base=str(tmp_path),
               no_telemetry=True)
    test = etcd_test(cfg)
    test["checker"] = Noop()
    h = run_test(test)["history"]
    assert h.columns is not None

    h2 = History.from_columns(h.columns)   # column-only view
    History.dict_materializations = 0
    subs = h2.split_by_key()
    assert subs, "register run produced no keyed subhistories"
    packs = wgl.pack_register_histories_batched(subs)
    assert History.dict_materializations == 0, \
        "columnar pipeline materialized dict ops"
    assert set(packs) == set(subs)
    assert all(p.ok for p in packs.values()), \
        {k: p.reason for k, p in packs.items() if not p.ok}

    # the packs are the SAME packs the dict path produces
    ref = wgl.pack_register_histories_batched(
        {k: History(list(s.ops)) for k, s in h.split_by_key().items()})
    import dataclasses
    import numpy as np
    for k, p in packs.items():
        q = ref[k]
        wgl.ensure_frames(p)
        wgl.ensure_frames(q)
        for fld in dataclasses.fields(type(p)):
            x, y = getattr(p, fld.name), getattr(q, fld.name)
            if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                assert np.array_equal(x, y), (k, fld.name)
            else:
                assert x == y, (k, fld.name, x, y)
