"""Same-seed columnar/dict equivalence fuzz (r6 tentpole guard).

The interpreter records every history twice: the dict op stream (the
serialization- and replay-compatible representation) and the typed SoA
columns (core/history.py OpColumns) the hot checker paths consume. This
suite pins the contract between the two:

- materializing the columns back to ops is *bit-identical* to the dict
  stream — index, time, process, type, f, value, and every extra key —
  for every workload, with and without nemeses;
- the composed checker reaches the same verdicts whether it is handed
  the dual-backed recorded history (columnar fast paths engaged) or a
  dict-only copy (reference paths);
- the flagship columnar pipeline — ``split_by_key`` into the batched
  register packer — runs without a single dict materialization
  (``History.dict_materializations`` stays 0).
"""

import json

import pytest

from jepsen_etcd_tpu.checkers.core import Noop
from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.runner.test_runner import run_test

#: one config per workload; nemesis mixes mirror the cross-run battery
#: at small time limits so the whole file stays tier-1-fast
CONFIGS = {
    "register-nemesis": dict(workload="register",
                             nodes=["n1", "n2", "n3"],
                             time_limit=5, rate=200, seed=11,
                             nemesis=["kill", "partition"],
                             nemesis_interval=2),
    "set-nemesis": dict(workload="set", time_limit=4, rate=200, seed=19,
                        nemesis=["pause", "clock"], nemesis_interval=2),
    "append-nemesis": dict(workload="append", nodes=["n1", "n2", "n3"],
                           time_limit=4, rate=150, seed=5,
                           nemesis=["partition"], nemesis_interval=2),
    "watch": dict(workload="watch", time_limit=4, rate=150, seed=9),
    "lock": dict(workload="lock", nodes=["n1", "n2", "n3"],
                 time_limit=5, rate=100, seed=13, nemesis=["kill"],
                 nemesis_interval=2),
    "wr": dict(workload="wr", nodes=["n1", "n2", "n3"],
               time_limit=4, rate=200, seed=21),
}


def _record(tmp_path, name):
    """Run the config's sim; returns (test, composed_checker, history).

    The run itself uses a Noop checker — the composed checker is
    exercised explicitly on both representations by the test."""
    cfg = dict(CONFIGS[name])
    cfg["store_base"] = str(tmp_path)
    cfg["no_telemetry"] = True
    test = etcd_test(cfg)
    checker = test["checker"]
    test["checker"] = Noop()
    out = run_test(test)
    return test, checker, out["history"]


def _strip(result) -> str:
    return json.dumps(result, sort_keys=True, default=repr)


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_columns_equivalent_and_verdicts_agree(tmp_path, name):
    test, checker, h = _record(tmp_path, name)
    cols = h.columns
    assert cols is not None, "recorded history lost its columns"
    assert len(cols) == len(h)

    # 1) column materialization is bit-identical to the dict stream
    back = History.from_columns(cols).ops
    assert len(back) == len(h.ops)
    for a, b in zip(h.ops, back):
        assert dict(a) == dict(b), (dict(a), dict(b))

    # 2) composed checker: columnar fast paths vs dict-only reference
    res_cols = checker.check(test, h)
    h_dict = History(list(h.ops))          # no columns attached
    assert h_dict.columns is None
    res_dict = checker.check(test, h_dict)
    assert _strip(res_cols) == _strip(res_dict)
    assert res_cols["valid?"] == res_dict["valid?"]


def test_columnar_register_pipeline_no_dict_materialization(tmp_path):
    """Tier-1 regression guard (r6 acceptance): the columnar checker
    path — split_by_key into the batched SoA register packer — must not
    round-trip through dict ops at all."""
    from jepsen_etcd_tpu.ops import wgl

    cfg = dict(workload="register", nodes=["n1", "n2", "n3"],
               time_limit=20, rate=0, ops_per_key=60, seed=17,
               snapshot_count=100_000, store_base=str(tmp_path),
               no_telemetry=True)
    test = etcd_test(cfg)
    test["checker"] = Noop()
    h = run_test(test)["history"]
    assert h.columns is not None

    h2 = History.from_columns(h.columns)   # column-only view
    History.dict_materializations = 0
    subs = h2.split_by_key()
    assert subs, "register run produced no keyed subhistories"
    packs = wgl.pack_register_histories_batched(subs)
    assert History.dict_materializations == 0, \
        "columnar pipeline materialized dict ops"
    assert set(packs) == set(subs)
    assert all(p.ok for p in packs.values()), \
        {k: p.reason for k, p in packs.items() if not p.ok}

    # the packs are the SAME packs the dict path produces
    ref = wgl.pack_register_histories_batched(
        {k: History(list(s.ops)) for k, s in h.split_by_key().items()})
    import dataclasses
    import numpy as np
    for k, p in packs.items():
        q = ref[k]
        wgl.ensure_frames(p)
        wgl.ensure_frames(q)
        for fld in dataclasses.fields(type(p)):
            x, y = getattr(p, fld.name), getattr(q, fld.name)
            if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                assert np.array_equal(x, y), (k, fld.name)
            else:
                assert x == y, (k, fld.name, x, y)
