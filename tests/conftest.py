"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
(pjit/shard_map over a Mesh) compile and execute without TPU hardware.

NOTE: this environment's axon site hook force-registers the tunneled TPU
backend and overrides JAX_PLATFORMS from the environment, so we must
override back via jax.config *before* any backend initialization.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# One persistent XLA compilation cache shared by the suite AND every
# spawned child (campaign pool workers, bench/CLI/service subprocesses
# inherit it through the environment): children stop recompiling
# kernels some other process already built, which is most of their
# startup on a small CI host.  jax picks both settings up from the
# environment at backend init; correctness is unaffected — the cache
# key covers the HLO, the flags, and the jax version.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/jepsen-etcd-tpu-xla-cache")
# only cache compiles worth sharing — the differential fuzz tests emit
# hundreds of sub-100ms single-shape compiles nothing ever reuses, and
# writing those costs more than they save
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.25")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # newer jax spells the device-count override as a config option; on
    # versions without it the XLA_FLAGS fallback above already forced 8
    # host devices before backend init
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
# NOTE: no enable_compile_cache() here — it would initialize backends
# (breaking the jax_num_cpu_devices update above) and is a no-op on the
# cpu backend anyway

import shutil  # noqa: E402

import pytest  # noqa: E402

#: real etcd binary, if one is on PATH (None in the hermetic CI image);
#: @pytest.mark.live tests depend on the fixture below and skip cleanly
ETCD_BINARY = shutil.which("etcd")


@pytest.fixture(scope="session")
def etcd_binary():
    """Path to a real etcd binary; skips the test when absent."""
    if ETCD_BINARY is None:
        pytest.skip("real etcd binary not on PATH — install etcd to "
                    "activate @pytest.mark.live tests")
    return ETCD_BINARY
