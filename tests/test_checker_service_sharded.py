"""Multi-device checker service (runner/checker_service.py): sticky
round-robin placement, per-device counter ledgers, single-group
shard_map dispatch, and verdict bit-identity across device counts.

The whole suite runs under conftest's forced 8-device CPU mesh, so
placement decisions are real: `jax.devices()` has eight chips and the
service must spread distinct (bucket, width) group shapes across them
while keeping each shape pinned to one chip (warm executables never
migrate).  The subprocess test re-runs the canonical 12-pack fuzz from
tests/test_checker_service.py under forced 8-device and 1-device
meshes and diffs the verdict projections — sharding must never change
a verdict.
"""

import json
import os
import random
import subprocess
import sys

from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.ops import wgl
from jepsen_etcd_tpu.runner import checker_service as svc_mod

from test_wgl import gen_history
from test_checker_service import make_packs, view, service  # noqa: F401

import jax

_N_DEV = len(jax.devices())


def _one_shape_packs(seed, n):
    """n packs sharing ONE group key (same bucket/info/width), so a
    single-request tick sees exactly one oversized group — the
    shard_map trigger."""
    rng = random.Random(seed)
    packs = []
    key = None
    while len(packs) < n:
        h = History(gen_history(rng, n_procs=3, n_ops=12,
                                info_rate=0.0))
        p = wgl.pack_register_history(h)
        if not (p.ok and p.R > 0):
            continue
        if key is None:
            key = wgl.group_key(p)
        if wgl.group_key(p) == key:
            packs.append(p)
    return packs


def test_device_name_is_explicit_per_device():
    assert svc_mod.device_name() == "cpu0"
    devs = jax.devices()
    names = [svc_mod.device_name(d) for d in devs]
    assert names == [f"cpu{d.id}" for d in devs]
    assert len(set(names)) == _N_DEV


def test_placement_round_robin_and_sticky():
    """Eight distinct group shapes land on eight distinct chips, and
    re-asking for any shape returns the original assignment."""
    assert _N_DEV == 8, "conftest forces an 8-device CPU mesh"
    pl = svc_mod.DevicePlacement()
    keys = [(16 * (1 << i), (0, 0, 0), 32) for i in range(8)]
    first = {k: pl.assign(k) for k in keys}
    assert {idx for idx, _ in first.values()} == set(range(8))
    assert all(d is not None for _, d in first.values())
    # sticky: a second pass (any order) changes nothing
    for k in reversed(keys):
        assert pl.assign(k) == first[k]
    snap = pl.snapshot()
    assert len(snap) == 8
    assert set(snap.values()) == {f"cpu{i}" for i in range(8)}


def test_groups_spread_and_per_device_ledger(service):  # noqa: F811
    """Mixed-shape fuzz through a live service: distinct group shapes
    spread round-robin over distinct chips, and the per-device
    dispatch counters sum exactly to the tick totals."""
    # same seeds/params as test_checker_service.py's fuzz so the
    # group shapes (and their compiled executables) are already warm
    packs = (make_packs(11, 5, info_rate=0.15)
             + make_packs(12, 3, corrupt=True))
    want = [view(o) for o in wgl.check_packed_batch(list(packs))]
    client = svc_mod.CheckerClient(service.path)
    outs = client.check(packs)
    assert outs is not None
    assert [view(o) for o in outs] == want
    st = service.stats()
    assert st["devices"] == [f"cpu{i}" for i in range(_N_DEV)]
    place = st["placement"]
    n_groups = len({wgl.group_key(p) for p in packs})
    assert len(place) == n_groups
    assert len(set(place.values())) == min(n_groups, _N_DEV)
    ctr = st["counters"]
    disp = {k: v for k, v in ctr.items()
            if k.startswith("service.device_dispatches.")}
    assert set(disp) <= {f"service.device_dispatches.cpu{i}"
                        for i in range(_N_DEV)}
    assert sum(disp.values()) == (ctr["service.group_ticks"]
                                  + ctr.get("service.shard_fanout", 0))
    assert ctr.get("service.device_occupancy", 0) == min(n_groups,
                                                         _N_DEV)
    client.close()


def test_single_oversized_group_shards_across_all_devices(
        service):  # noqa: F811
    """One group of 2*n_dev packs in a tick takes the shard_map path:
    the batch axis spreads over EVERY chip, the fan-out is ledgered
    per device, and verdicts stay bit-identical to local checking."""
    packs = _one_shape_packs(31, 2 * _N_DEV)
    want = [view(o) for o in wgl.check_packed_batch(list(packs))]
    client = svc_mod.CheckerClient(service.path)
    outs = client.check(packs)
    assert outs is not None
    assert [view(o) for o in outs] == want
    ctr = service.stats()["counters"]
    assert ctr.get("service.sharded_ticks", 0) >= 1, ctr
    disp = {k: v for k, v in ctr.items()
            if k.startswith("service.device_dispatches.")}
    assert set(disp) == {f"service.device_dispatches.cpu{i}"
                         for i in range(_N_DEV)}, disp
    assert sum(disp.values()) == (ctr["service.group_ticks"]
                                  + ctr["service.shard_fanout"]), ctr
    client.close()


def test_verdicts_identical_across_device_counts(tmp_path):
    """The satellite's subprocess bar: the same 12-pack fuzz through
    an 8-device service and a 1-device service (each under its own
    forced XLA device count) yields bit-identical verdict
    projections.  Children also self-assert round-robin spread,
    sticky reuse, and the per-device ledger (see
    sharded_service_child.py).  Both children run concurrently."""
    child = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "sharded_service_child.py")
    repo = os.path.dirname(os.path.dirname(child))

    def spawn(n_dev):
        env = dict(os.environ)
        env["XLA_FLAGS"] = \
            f"--xla_force_host_platform_device_count={n_dev}"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo + os.pathsep + \
            env.get("PYTHONPATH", "")
        env["TMPDIR"] = str(tmp_path)
        return subprocess.Popen(
            [sys.executable, child, str(n_dev)], env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    procs = {n: spawn(n) for n in (8, 1)}
    outs = {}
    for n, proc in procs.items():
        stdout, stderr = proc.communicate(timeout=540)
        assert proc.returncode == 0, (n, stderr[-4000:])
        outs[n] = json.loads(stdout.strip().splitlines()[-1])
    assert len(outs[8]) == 12
    assert outs[8] == outs[1]
