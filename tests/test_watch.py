"""Watch workload tests: converger convergence + crash propagation
(mirroring the reference's watch_test.clj:9-35), the edit-distance
kernel, the watch checker, and an end-to-end run."""

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop, sleep
from jepsen_etcd_tpu.workloads.watch import Converger, ConvergeBroken, \
    ConvergeTimeout
from jepsen_etcd_tpu.ops.edit_distance import (edit_distance,
                                               _indel_python)
from jepsen_etcd_tpu.checkers.watch import WatchChecker, canonical_log

SECOND = 1_000_000_000


# ---- converger ------------------------------------------------------------

@pytest.fixture
def sim_loop():
    yield
    set_current_loop(None)


def _loop(seed):
    l = SimLoop(seed=seed)
    set_current_loop(l)
    return l


def test_converger_basics(sim_loop):
    # append random numbers to lists until all final numbers agree
    # (watch_test.clj:11-22)
    loop = _loop(5)
    n = 3
    c = Converger(n, lambda vs: len({v[-1] for v in vs}) == 1)
    results = []

    async def worker(i):
        async def evolve(coll):
            await sleep(loop.rng.randint(0, 2_000_000))
            return coll + [loop.rng.randint(0, 1)]
        results.append((i, await c.converge(60 * SECOND, [i], evolve)))

    for i in range(n):
        loop.spawn(worker(i), f"w{i}")
    loop.run()
    assert len(results) == n
    # starts with initial values, ends converged
    for i, v in results:
        assert v[0] == i
    assert len({v[-1] for _, v in results}) == 1


def test_converger_crash_propagates(sim_loop):
    loop = _loop(6)
    n = 3
    c = Converger(n, lambda vs: len(set(vs)) == 1)
    outcomes = {}

    async def worker(i):
        async def evolve(v):
            await sleep(1_000_000)
            if i == 1:
                raise RuntimeError("hi")
            return loop.rng.randint(0, 1)
        try:
            outcomes[i] = ("ok", await c.converge(60 * SECOND, i, evolve))
        except RuntimeError as e:
            outcomes[i] = ("raised", str(e))
        except ConvergeBroken:
            outcomes[i] = ("broken", None)

    for i in range(n):
        loop.spawn(worker(i), f"w{i}")
    loop.run()
    assert outcomes[1] == ("raised", "hi")
    assert outcomes[0][0] == "broken"
    assert outcomes[2][0] == "broken"


def test_converger_timeout_returns_partial(sim_loop):
    loop = _loop(7)
    c = Converger(2, lambda vs: len(set(vs)) == 1)
    out = {}

    async def worker(i):
        async def evolve(v):
            await sleep(SECOND)
            return i  # never converges: 0 vs 1
        try:
            out[i] = await c.converge(5 * SECOND, i, evolve)
        except ConvergeTimeout as e:
            out[i] = ("timeout", e.value)

    for i in range(2):
        loop.spawn(worker(i), f"w{i}")
    loop.run()
    assert any(isinstance(v, tuple) and v[0] == "timeout"
               for v in out.values())


def test_converger_same_instant_wakeups(sim_loop):
    # two participants whose evolves complete at the same sim instant:
    # the signal must not be lost between spawn and first await
    loop = _loop(8)
    c = Converger(2, lambda vs: len(set(vs)) == 1)
    out = {}

    async def worker(i):
        async def evolve(v):
            await sleep(SECOND)  # identical, deterministic durations
            return 7
        out[i] = await c.converge(60 * SECOND, i, evolve)

    for i in range(2):
        loop.spawn(worker(i), f"w{i}")
    loop.run()
    assert out == {0: 7, 1: 7}
    assert loop.now < 10 * SECOND  # converged promptly, not via deadline


# ---- edit distance --------------------------------------------------------

def test_indel_basics():
    assert _indel_python([], []) == 0
    assert _indel_python([1, 2, 3], [1, 2, 3]) == 0
    assert _indel_python([1, 2, 3], [1, 3]) == 1
    assert _indel_python([1, 2], [3, 4]) == 4
    assert _indel_python([1, 2, 3], [2, 3, 4]) == 2


@pytest.mark.parametrize("n,m", [(0, 5), (7, 7), (40, 37), (200, 190)])
def test_edit_distance_kernel_matches_python(n, m):
    import numpy as np
    rng = np.random.default_rng(n * 100 + m)
    a = list(rng.integers(0, 5, n))
    b = list(rng.integers(0, 5, m))
    assert edit_distance(a, b, force_device=True) == _indel_python(a, b)


def test_edit_distance_on_strings():
    assert edit_distance(list("kitten"), list("sitting"),
                         force_device=True) == 5  # indel (no substitution)


# ---- checker --------------------------------------------------------------

def H(*ops):
    return History([Op(o) for o in ops])


def watch_ok(p, log, rev):
    return {"type": "ok", "process": p, "f": "watch",
            "value": {"revision": rev, "log": log}}


def watch_inv(p):
    return {"type": "invoke", "process": p, "f": "watch", "value": None}


def test_canonical_log_mode_beats_longest():
    assert canonical_log([[1, 2], [1, 2], [1, 2, 3]]) == [1, 2]
    assert canonical_log([[1], [1, 2, 3]]) == [1, 2, 3]


def test_watch_checker_identical_logs_valid():
    h = H(watch_inv(0), watch_ok(0, [1, 2, 3], 5),
          watch_inv(1), watch_ok(1, [1, 2, 3], 5))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is True


def test_watch_checker_divergent_logs_invalid():
    h = H(watch_inv(0), watch_ok(0, [1, 2, 3], 5),
          watch_inv(1), watch_ok(1, [1, 3, 2], 5),
          watch_inv(2), watch_ok(2, [1, 2, 3], 5))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is False
    assert r["deltas"][0]["thread"] == 1
    assert r["deltas"][0]["edit-distance"] == 2


def test_watch_checker_unequal_revisions_unknown():
    h = H(watch_inv(0), watch_ok(0, [1, 2], 4),
          watch_inv(1), watch_ok(1, [1, 2, 3], 5))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] == "unknown"


def test_watch_checker_nonmonotonic_invalid():
    h = H(watch_inv(0), watch_ok(0, [1], 5),
          watch_inv(1),
          {"type": "fail", "process": 1, "f": "watch",
           "error": ["nonmonotonic-watch", "went backwards"]})
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is False
    assert r["nonmonotonic-errors"]


def test_watch_checker_threads_fold_processes():
    # process 9 with concurrency 4 is thread 1: logs concatenate
    h = H(watch_inv(1), watch_ok(1, [1, 2], 3),
          watch_inv(9), watch_ok(9, [3, 4], 9),
          watch_inv(2), watch_ok(2, [1, 2, 3, 4], 9))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is True


# ---- end-to-end -----------------------------------------------------------

def test_watch_workload_e2e(tmp_path):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    out = run_test(etcd_test({
        "workload": "watch", "time_limit": 8, "rate": 50,
        "store_base": str(tmp_path), "seed": 13}))
    wl = out["results"]["workload"]
    assert wl["valid?"] is True, wl
    # watchers actually observed writes
    assert sum(wl["revisions"].values()) > 0


def test_edit_distance_batch_matches_single():
    import random
    from jepsen_etcd_tpu.ops.edit_distance import (
        edit_distance, edit_distance_batch, _indel_python)
    rng = random.Random(8)
    canonical = [rng.randrange(6) for _ in range(200)]
    logs = []
    for _ in range(5):
        log = list(canonical)
        for _ in range(rng.randrange(0, 12)):   # random indels
            if log and rng.random() < 0.5:
                log.pop(rng.randrange(len(log)))
            else:
                log.insert(rng.randrange(len(log) + 1), rng.randrange(6))
        logs.append(log)
    logs.append([])                              # empty log edge case
    batch = edit_distance_batch(canonical, logs, force_device=True)
    for log, got in zip(logs, batch):
        assert got == _indel_python(canonical, log)
        assert got == edit_distance(canonical, log, force_device=True)


def test_edit_distance_pallas_matches_python():
    """The single-launch pallas wavefront (interpret mode off-TPU) must
    agree with the Python DP, including empty-log and heavy-divergence
    edges."""
    import random
    from jepsen_etcd_tpu.ops.edit_distance import (
        edit_distance_batch, _indel_python)
    rng = random.Random(11)
    canonical = [rng.randrange(6) for _ in range(150)]
    logs = [[], list(reversed(canonical)), canonical[:70]]
    for _ in range(4):
        log = list(canonical)
        for _ in range(rng.randrange(0, 15)):
            if log and rng.random() < 0.5:
                log.pop(rng.randrange(len(log)))
            else:
                log.insert(rng.randrange(len(log) + 1), rng.randrange(6))
        logs.append(log)
    got = edit_distance_batch(canonical, logs, force_device=True,
                              force_pallas=True)
    want = [_indel_python(canonical, log) for log in logs]
    assert got == want, (got, want)


# ---- compaction gaps (final-watch restart, watch.clj:243-267) -------------

def gapped_ok(p, log, revs, rev, gaps):
    return {"type": "ok", "process": p, "f": "final-watch",
            "value": {"revision": rev, "log": log, "revs": revs,
                      "gaps": gaps}}


def full_ok(p, log, revs, rev):
    return {"type": "ok", "process": p, "f": "final-watch",
            "value": {"revision": rev, "log": log, "revs": revs}}


def test_watch_checker_gap_attributed_valid():
    """A thread missing exactly the values inside its recorded
    compaction window is legitimate: the events were destroyed."""
    h = H(watch_inv(0), full_ok(0, [10, 11, 12, 13], [2, 3, 4, 5], 5),
          watch_inv(1), full_ok(1, [10, 11, 12, 13], [2, 3, 4, 5], 5),
          # thread 2 saw 10 (rev 2), was compacted over (2, 4], resumed
          watch_inv(2), gapped_ok(2, [10, 13], [2, 5], 5, [[2, 4]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is True, r


def test_watch_checker_gap_unattributed_invalid():
    """Missing a value whose revision lies OUTSIDE every recorded gap is
    a real loss, gap or no gap."""
    h = H(watch_inv(0), full_ok(0, [10, 11, 12, 13], [2, 3, 4, 5], 5),
          watch_inv(1), full_ok(1, [10, 11, 12, 13], [2, 3, 4, 5], 5),
          # gap covers (2, 3] but value 12 (rev 4) is missing too
          watch_inv(2), gapped_ok(2, [10, 13], [2, 5], 5, [[2, 3]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is False
    d = [d for d in r["deltas"] if d["thread"] == 2][0]
    assert 12 in d["unattributed-missing"]


def test_watch_checker_gap_out_of_order_invalid():
    """A gapped log must still be an in-order subsequence of canonical."""
    h = H(watch_inv(0), full_ok(0, [10, 11, 12, 13], [2, 3, 4, 5], 5),
          watch_inv(1), full_ok(1, [10, 11, 12, 13], [2, 3, 4, 5], 5),
          watch_inv(2), gapped_ok(2, [13, 10], [5, 2], 5, [[2, 4]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is False


def test_watch_checker_gapped_log_never_defines_canonical():
    """With one full and one gapped log, canonical is the full one even
    if the gapped log is longer-listed first."""
    h = H(watch_inv(2), gapped_ok(2, [10, 13], [2, 5], 5, [[2, 4]]),
          watch_inv(0), full_ok(0, [10, 11, 12, 13], [2, 3, 4, 5], 5))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is True, r


def test_watch_checker_dup_value_no_revs_end_anchored_rescue():
    """Duplicate canonical value, gapped thread with NO recorded revs
    that saw only the LATER occurrence: start-anchored greedy matching
    would misassign the sighting to the earlier occurrence and flag the
    later revision (outside the gap) missing — a false violation. The
    end-anchored pass attributes every miss to the gap."""
    h = H(watch_inv(0), full_ok(0, [10, 11, 10, 13], [2, 3, 4, 5], 5),
          watch_inv(1), full_ok(1, [10, 11, 10, 13], [2, 3, 4, 5], 5),
          # thread 2 saw the rev-4 occurrence of 10; gap covers revs 2-3
          watch_inv(2), gapped_ok(2, [10, 13], [], 5, [[1, 3]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is True, r


def test_watch_checker_dup_value_no_revs_ambiguous_is_unknown():
    """Duplicate canonical value, no recorded revs, and NEITHER
    anchoring attributes every miss: the evidence is ambiguous, so the
    verdict downgrades to unknown instead of a definite violation."""
    h = H(watch_inv(0), full_ok(0, [10, 11, 10], [2, 3, 4], 4),
          watch_inv(1), full_ok(1, [10, 11, 10], [2, 3, 4], 4),
          # gap covers only rev 3; whichever occurrence of 10 the
          # sighting is assigned to, the other one's miss is outside
          watch_inv(2), gapped_ok(2, [10], [], 4, [[2, 3]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] == "unknown", r
    assert any(d.get("indefinite") for d in r["deltas"])


def test_watch_admin_compaction_gap_e2e(tmp_path):
    """Aggressive admin (compact/defrag) cadence that compacts under the
    final watch: the watcher must restart past the compact horizon,
    record a gap, and the run must end green — this exact scenario used
    to stall the converger and end `unknown` (VERDICT r2 weak #5)."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    out = run_test(etcd_test({
        "workload": "watch", "nemesis": ["admin"],
        "nemesis_interval": 1.5, "time_limit": 40, "rate": 200,
        "store_base": str(tmp_path), "seed": 9}))
    wl = out["results"]["workload"]
    assert wl["valid?"] is True, wl
    gapped = [op for op in out["history"]
              if op.get("type") == "ok" and op.get("f") == "final-watch"
              and (op.get("value") or {}).get("gaps")]
    assert gapped, "seed 9 must exercise the compaction-gap restart"


def test_watch_checker_all_threads_gapped_merged_canonical():
    """With every watcher gapped (aggressive admin), canonical must be
    the union of observations merged by revision — no single gapped log
    can define consensus without false data-loss verdicts."""
    h = H(watch_inv(0), gapped_ok(0, [10, 13, 14], [2, 5, 6], 6,
                                  [[2, 4]]),
          watch_inv(1), gapped_ok(1, [10, 11, 12, 14], [2, 3, 4, 6], 6,
                                  [[4, 5]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is True, r


def test_watch_checker_all_gapped_real_loss_still_caught():
    """Union canonical still catches a loss outside every gap window."""
    h = H(watch_inv(0), gapped_ok(0, [10, 11, 12, 13], [2, 3, 4, 5], 6,
                                  [[5, 6]]),
          # thread 1 missed value 12 (rev 4), outside its (5,6] gap
          watch_inv(1), gapped_ok(1, [10, 11, 13], [2, 3, 5], 6,
                                  [[5, 6]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is False
    d = [d for d in r["deltas"] if d["thread"] == 1][0]
    assert 12 in d["unattributed-missing"]


def test_watch_member_failover_e2e(tmp_path):
    """A watcher pinned to a node the member nemesis shrinks away must
    fail over to a current member (jetcd's multi-endpoint channel
    semantics) — previously it retried connect-failed until the
    converger timed out and the run ended unknown."""
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test
    out = run_test(etcd_test({
        "workload": "watch", "nemesis": ["member", "admin"],
        "time_limit": 30, "rate": 100,
        "store_base": str(tmp_path), "seed": 0}))
    wl = out["results"]["workload"]
    assert wl["valid?"] is True, wl
    assert out["valid?"] is True


def test_watch_checker_dup_value_unique_miss_stays_definite():
    """A duplicate value elsewhere in canonical must not excuse a
    definite miss of a UNIQUE value: no re-anchoring can ever move it
    into a gap, so the violation stays False, not unknown."""
    h = H(watch_inv(0), full_ok(0, [10, 11, 10, 20], [2, 3, 4, 5], 5),
          watch_inv(1), full_ok(1, [10, 11, 10, 20], [2, 3, 4, 5], 5),
          # thread 2 saw everything except unique value 20 (rev 5);
          # its gap covers nothing near rev 5
          watch_inv(2), gapped_ok(2, [10, 11, 10], [], 5, [[0, 1]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is False, r


def test_watch_checker_dup_value_no_sighting_stays_definite():
    """Every occurrence of a duplicated value missing (the thread never
    sighted it at all): no assignment ambiguity exists, so an
    out-of-gap miss stays a definite violation."""
    h = H(watch_inv(0), full_ok(0, [10, 11, 10], [2, 3, 4], 4),
          watch_inv(1), full_ok(1, [10, 11, 10], [2, 3, 4], 4),
          # thread 2 saw only 11; rev-4 occurrence of 10 is outside the
          # gap under EVERY assignment
          watch_inv(2), gapped_ok(2, [11], [], 4, [[1, 2]]))
    r = WatchChecker().check({"concurrency": 4}, h)
    assert r["valid?"] is False, r
