"""Subprocess half of tests/test_checker_service_sharded.py (NOT a
pytest module — invoked as ``python sharded_service_child.py <n_dev>``
with ``XLA_FLAGS=--xla_force_host_platform_device_count=<n_dev>``).

Runs the shared 12-pack mixed valid/corrupt/info fuzz from
tests/test_checker_service.py through a live CheckerService under the
forced device count, asserts the multi-device invariants IN the child
when a mesh is visible (round-robin spread, sticky placement reuse,
per-device counters summing to tick totals), and prints the verdict
projections as one JSON line. The parent test diffs the 8-device
child's projections against the 1-device child's: verdict bit-identity
across device counts is the whole soundness bar for the sharded
dispatcher.
"""

import json
import os
import sys


def main() -> int:
    n_dev = int(sys.argv[1])
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax
    assert len(jax.devices()) == n_dev, (n_dev, jax.devices())

    from test_checker_service import make_packs, view
    from jepsen_etcd_tpu.ops import wgl
    from jepsen_etcd_tpu.runner import checker_service as svc_mod

    packs = (make_packs(11, 6, info_rate=0.15)
             + make_packs(12, 4, corrupt=True)
             + make_packs(13, 2, info_rate=0.5))
    svc = svc_mod.CheckerService(tick_s=0.01).start()
    try:
        client = svc_mod.CheckerClient(svc.path)
        outs = client.check(packs)
        assert outs is not None, "service unreachable"
        place1 = dict(svc.stats().get("placement") or {})
        if n_dev > 1:
            # second round, same packs: sticky placement must REUSE
            # every assignment (warm executables never migrate).  Only
            # meaningful with a mesh — the 1-device child has nowhere
            # to migrate to, so it skips straight to the verdict dump
            outs2 = client.check(packs)
            assert outs2 is not None, "service unreachable (round 2)"
            st = svc.stats()
            assert dict(st.get("placement") or {}) == place1, \
                (place1, st.get("placement"))
            for a, b in zip(outs, outs2):
                assert view(a) == view(b), (view(a), view(b))
        else:
            st = svc.stats()
        ctr = st.get("counters") or {}
        disp = {k: v for k, v in ctr.items()
                if k.startswith("service.device_dispatches.")}
        assert disp, sorted(ctr)
        # per-device ledger: Σ dispatches over chips balances the
        # group ledger exactly (fan-counted sharded lanes included)
        assert sum(disp.values()) == \
            (ctr.get("service.group_ticks", 0)
             + ctr.get("service.shard_fanout", 0)), ctr
        assert len(st.get("devices") or []) == n_dev, st.get("devices")
        if n_dev > 1:
            groups = {wgl.group_key(p) for p in packs}
            # round-robin: distinct group shapes spread over distinct
            # chips (as many chips as shapes, capped by the mesh)
            assert len({v for v in place1.values()}) == \
                min(len(groups), n_dev), (groups, place1)
        client.close()
    finally:
        svc.close()
    print(json.dumps([view(o) for o in outs]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
