"""Streaming online checking (ISSUE 8): chunked frontier resume,
chunk drains, the stream feed's failure modes, and the sliding-window
soak loop.

Bit-identity is the contract everywhere: ``check_prefix``'s wave
budget only chooses WHERE the BFS pauses (frontier contents, rung
escalations, spill hand-off and the verdict dict match the one-shot
ladder for every budget); ``take_chunk`` drains are non-destructive
(``finish()`` still returns the complete columns); a consumer that
trips on a malformed stream withdraws its hints instead of tainting
the run. The verdict-level equivalence fuzz lives in
tests/test_columns_equiv.py; the soak e2e here drives the real CLI
pipeline against the fake-etcd stub.
"""

import gc
import json
import random
import weakref

import pytest

from jepsen_etcd_tpu.core.history import ColumnsBuilder, History
from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.ops import wgl

from test_wgl import gen_history

BUDGETS = (1, 3, 64, 100_000)


def _run_prefix(p, max_waves, spill=True):
    """Drive check_prefix to completion at a fixed wave budget."""
    state = wgl.check_prefix(p, None, max_waves=max_waves, spill=spill)
    steps = 1
    while not state.done:
        state = wgl.check_prefix(p, state, max_waves=max_waves,
                                 spill=spill)
        steps += 1
        assert steps < 100_000, "check_prefix failed to converge"
    return state


def _strip_result(out):
    # the frozen-frontier hand-off is identity-compared elsewhere; for
    # verdict equality compare everything JSON-expressible
    return json.dumps({k: v for k, v in out.items() if k != "_resume"},
                      sort_keys=True, default=repr)


@pytest.mark.parametrize("seed", [7, 21, 404])
def test_check_prefix_matches_one_shot_across_budgets(seed):
    rng = random.Random(seed)
    h = gen_history(rng, n_procs=rng.randint(3, 6),
                    n_ops=rng.randint(16, 48),
                    info_rate=0.1 if seed % 2 else 0.0)
    p = wgl.pack_register_history(h)
    if not p.ok:
        pytest.skip(f"pack delegated: {p.reason}")
    ref = wgl.check_packed(p)
    results = {}
    for budget in BUDGETS:
        state = _run_prefix(p, budget)
        assert state.done and state.result is not None
        results[budget] = state
        # the budget must not leak into the verdict
        assert _strip_result(state.result) == \
            _strip_result(results[BUDGETS[0]].result), budget
        assert state.waves_run == results[BUDGETS[0]].waves_run
    # and the chunked ladder agrees with the one-shot ladder verdict
    assert results[BUDGETS[0]].result["valid?"] == ref["valid?"]
    if "waves" in ref and "waves" in results[BUDGETS[0]].result:
        assert results[BUDGETS[0]].result["waves"] == ref["waves"]


def test_check_prefix_rung_escalation_deterministic():
    """A history wide enough to overflow the first rung escalates the
    ladder identically at every budget — pause points never change
    WHERE the frontier grows."""
    rng = random.Random(31)
    found = None
    for _ in range(60):
        h = gen_history(rng, n_procs=10, n_ops=60, values=4,
                        info_rate=0.25, dur_scale=6.0)
        p = wgl.pack_register_history(h)
        if not p.ok:
            continue
        out = wgl.check_packed(p)
        if out.get("rungs", 1) >= 2:
            found = (p, out)
            break
    assert found is not None, "no rung-escalating history found"
    p, ref = found
    for budget in BUDGETS:
        state = _run_prefix(p, budget)
        assert state.result["rungs"] == ref["rungs"], budget
        assert _strip_result(state.result) == _strip_result(ref), budget


def test_check_prefix_trivial_and_unpackable():
    empty = wgl.pack_register_history(History([]))
    state = wgl.check_prefix(empty)
    assert state.done and state.result["valid?"] is True

    bad = wgl.Packed(ok=False, reason="delegated")
    state = wgl.check_prefix(bad)
    assert state.done
    assert state.result["valid?"] == "unknown"
    assert state.result["reason"] == "delegated"


# ---- adversarial chunk boundaries (ISSUE 19 satellite) ---------------------
#
# The fused pipeline's consumer packs each history by feeding
# _slice_columns row windows through a PackStream. Its soundness
# argument is that chunk boundaries are INVISIBLE: however the row
# stream is cut — including between an op's invoke and its completion,
# the worst case for any packer holding per-process open-op state —
# the per-key packs and every check_prefix pause along the frontier's
# trajectory are bit-identical to the one-shot run.


def _fused_history(seed=5):
    from jepsen_etcd_tpu.simbatch import BatchConfig, generate_jax
    cfg = BatchConfig(workload="register", lanes=6, ops_per_lane=40,
                      rate=500.0, keys=2)
    return generate_jax(cfg, [seed])["histories"][0]


def _pack_split(cols, bounds):
    """Pack a column stream cut at the given row offsets."""
    from jepsen_etcd_tpu.runner.stream import _slice_columns
    ps = wgl.PackStream()
    cuts = [0] + sorted(set(bounds)) + [len(cols)]
    for lo, hi in zip(cuts, cuts[1:]):
        if hi > lo:
            ps.feed(_slice_columns(cols, lo, hi))
    packs = ps.finish()
    assert packs is not None and ps.ok
    return packs


def _mid_window_cuts(cols):
    """Row offsets that each split some op's invoke from its
    completion: cut right after every 7th invoke whose matching
    completion lies strictly later in the stream."""
    open_rows = {}
    pairs = []
    for i in range(len(cols)):
        p = int(cols.proc[i])
        if int(cols.type_code[i]) == 0:          # invoke
            open_rows[p] = i
        elif p in open_rows:
            pairs.append((open_rows.pop(p), i))
    cuts = [inv + 1 for inv, comp in pairs if comp > inv + 1]
    assert cuts, "history has no spanning invoke windows"
    return cuts[::7] or cuts[:1]


def _prefix_trajectory(p, max_waves):
    """Every pause point of a budgeted run: (k, rung, rungs, waves,
    frontier-bytes) per step, plus the finished state."""
    import hashlib

    import numpy as np

    def snap(state):
        fr = b"".join(np.asarray(x).tobytes() for x in state.frontier) \
            if getattr(state, "frontier", None) is not None else b""
        return (int(state.k) if not state.done else None,
                state.rungs, state.waves_run,
                hashlib.sha256(fr).hexdigest())

    state = wgl.check_prefix(p, None, max_waves=max_waves)
    traj = [snap(state)]
    steps = 1
    while not state.done:
        state = wgl.check_prefix(p, state, max_waves=max_waves)
        traj.append(snap(state))
        steps += 1
        assert steps < 100_000, "check_prefix failed to converge"
    return traj, state


def test_packstream_chunk_boundaries_are_invisible():
    """Every cut pattern — one row per chunk, prime-width chunks, and
    cuts deliberately splitting invoke windows — yields per-key packs
    bit-identical to the one-shot feed."""
    import dataclasses

    import numpy as np
    from jepsen_etcd_tpu.runner.stream import _slice_columns

    cols = _fused_history().columns
    ps = wgl.PackStream()
    ps.feed(_slice_columns(cols, 0, len(cols)))
    ref = ps.finish()
    assert ref is not None and ps.ok
    n = len(cols)
    patterns = {"per-row": list(range(1, n)),
                "prime": list(range(13, n, 13)),
                "mid-window": _mid_window_cuts(cols)}
    for name, bounds in patterns.items():
        packs = _pack_split(cols, bounds)
        assert sorted(packs) == sorted(ref), name
        for key, pk in packs.items():
            wgl.ensure_frames(pk)
            wgl.ensure_frames(ref[key])
            for fld in dataclasses.fields(type(pk)):
                x = getattr(pk, fld.name)
                y = getattr(ref[key], fld.name)
                if isinstance(x, np.ndarray) or \
                        isinstance(y, np.ndarray):
                    assert np.array_equal(x, y), (name, key, fld.name)
                else:
                    assert x == y, (name, key, fld.name)


def test_check_prefix_resume_under_adversarial_boundaries():
    """The full fused-consumer leg: packs built from mid-invoke-window
    chunk cuts drive check_prefix through identical frontier
    trajectories — every pause's k, rung count and frontier bytes —
    as packs from the unsplit stream, at every wave budget."""
    from jepsen_etcd_tpu.runner.stream import _slice_columns

    cols = _fused_history(seed=9)
    cols = cols.columns
    ps = wgl.PackStream()
    ps.feed(_slice_columns(cols, 0, len(cols)))
    ref_packs = ps.finish()
    assert ref_packs is not None
    split_packs = _pack_split(cols, _mid_window_cuts(cols))
    for key in sorted(ref_packs):
        for budget in BUDGETS:
            t_ref, s_ref = _prefix_trajectory(ref_packs[key], budget)
            t_spl, s_spl = _prefix_trajectory(split_packs[key], budget)
            assert t_ref == t_spl, (key, budget)
            assert _strip_result(s_ref.result) == \
                _strip_result(s_spl.result), (key, budget)


def _op(i, type, process, f, value, error=None):
    d = dict(type=type, process=process, f=f, value=value,
             time=i * 10, index=i)
    if error is not None:
        d["error"] = error
    return Op(d)


def test_take_chunk_drains_and_preserves_finish():
    b = ColumnsBuilder()
    assert b.take_chunk() is None          # nothing recorded yet
    ops = [_op(0, "invoke", 0, "read", (0, [None, None])),
           _op(1, "ok", 0, "read", (0, [0, None])),
           _op(2, "invoke", 1, "write", (0, [None, 3])),
           _op(3, "ok", 1, "write", (0, [1, 3]))]
    for op in ops[:2]:
        b.append(op)
    c1 = b.take_chunk()
    assert c1 is not None and len(c1) == 2
    assert b.take_chunk() is None          # cursor caught up
    for op in ops[2:]:
        b.append(op)
    c2 = b.take_chunk()
    assert c2 is not None and len(c2) == 2
    # intern tables are shared by reference: chunk codes resolve
    # against the final tables
    assert c1.f_table is b.f_table and c2.key_table is b.key_table
    # the drain is non-destructive: finish() still has every row
    full = b.finish()
    assert full is not None and len(full) == 4
    assert [dict(o) for o in History.from_columns(full).ops] == \
        [dict(o) for o in ops]


def test_take_chunk_dead_builder():
    b = ColumnsBuilder()
    b.append(_op(0, "invoke", 0, "read", (0, [None, None])))
    b.dead = True
    assert b.take_chunk() is None
    assert b.finish() is None


def test_stream_feed_withdraws_hint_on_undelegatable_stream():
    """A register stream the columnar packer can't express (non-int
    payload) silently drops the register_packs hint — stats survive,
    correctness never depended on the artifact."""
    from jepsen_etcd_tpu.runner.stream import StreamFeed

    ops = [_op(0, "invoke", 0, "write", (0, [None, "s"])),
           _op(1, "ok", 0, "write", (0, [1, "s"]))]
    h = History(ops)
    carrier = {"workload": "register"}
    feed = StreamFeed(carrier, chunk_ops=1)
    b = ColumnsBuilder()
    feed.attach(b)
    for op in ops:
        b.append(op)
        feed.on_record()
    hints = feed.finish(h)
    assert feed.error is None              # delegation is not an error
    assert hints["stats"]["rows"] == len(h)
    assert "register_packs" not in hints
    assert carrier["_stream"] is hints


def test_stream_feed_short_feed_withdraws_artifacts():
    """Hints must cover the WHOLE history: a feed that saw fewer rows
    than the final history installs stats only."""
    from jepsen_etcd_tpu.runner.stream import StreamFeed

    ops = [_op(0, "invoke", 0, "write", (0, [None, 1])),
           _op(1, "ok", 0, "write", (0, [1, 1]))]
    longer = History(ops + [_op(2, "invoke", 1, "read",
                                (0, [None, None]))])
    feed = StreamFeed({"workload": "register"}, chunk_ops=1)
    b = ColumnsBuilder()
    feed.attach(b)
    for op in ops:
        b.append(op)
        feed.on_record()
    hints = feed.finish(longer)            # 2 rows fed, 3 in history
    assert hints["stats"]["rows"] == 2
    assert "register_packs" not in hints


@pytest.mark.soak
def test_soak_three_windows_fake_etcd(tmp_path):
    """ISSUE 8 acceptance: the soak loop sustains >= 3 windows against
    one long-lived (fake-etcd) cluster — per-window verdicts all True,
    register key space rotated every window, and each window's history
    RELEASED before the next runs (bounded memory)."""
    from jepsen_etcd_tpu.runner.test_runner import (SOAK_KEY_STRIDE,
                                                    run_soak)

    refs = []

    def on_window(summary, out):
        refs.append(weakref.ref(out["history"]))
        return None

    opts = dict(workload="register", nodes=["n1"],
                client_type="http", db_mode="local",
                etcd_binary="fake", etcd_data_dir=str(tmp_path / "data"),
                # rate 150, not 50: the stats checker reads "unknown"
                # when a window's every cas loses its value-guess
                # lottery, so give each window enough attempts that
                # P(no cas ever succeeds) is negligible
                rate=150, ops_per_key=20, seed=3,
                soak=True, soak_windows=3, soak_window_s=2,
                store_base=str(tmp_path), no_telemetry=True)
    out = run_soak(opts, on_window=on_window)
    assert out["count"] == 3
    assert out["valid?"] is True
    assert [w["valid?"] for w in out["windows"]] == [True, True, True]
    assert [w["window"] for w in out["windows"]] == [0, 1, 2]
    offsets = [w["key_offset"] for w in out["windows"]]
    assert offsets == [0, SOAK_KEY_STRIDE, 2 * SOAK_KEY_STRIDE]
    assert all(w["ops"] > 0 for w in out["windows"])
    # bounded memory: every window's history is collectable once the
    # loop moved on (run_soak keeps summaries only)
    out = None
    gc.collect()
    assert len(refs) == 3
    assert all(r() is None for r in refs), \
        "soak retained a window's history"


@pytest.mark.soak
def test_soak_net_fault_schedule_heal_restores_progress(tmp_path):
    """ISSUE 13 satellite: the net plane rides under --soak. Windows
    cycle [healthy, drop:1.0]; the lossy window is held for the WHOLE
    window on the shared proxy plane (total chunk loss: every op times
    out), and the heal between windows restores progress on the SAME
    retained cluster — window 2 succeeds again."""
    from jepsen_etcd_tpu.runner.test_runner import run_soak

    ok_counts = []

    def on_window(summary, out):
        ok_counts.append(sum(1 for op in out["history"].ops
                             if op.get("type") == "ok"))
        return None

    opts = dict(workload="register", nodes=["n1"],
                client_type="http", db_mode="local",
                etcd_binary="fake", etcd_data_dir=str(tmp_path / "data"),
                rate=150, ops_per_key=20, seed=3, time_limit=2,
                soak=True, soak_windows=3, soak_window_s=2,
                soak_net_faults=["drop:1.0"],
                store_base=str(tmp_path), no_telemetry=True)
    out = run_soak(opts, on_window=on_window)
    assert out["count"] == 3
    faults = [w["soak-fault"] for w in out["windows"]]
    assert faults == [None, "drop:1.0", None]
    # healthy windows make real progress and check clean
    assert out["windows"][0]["valid?"] is True and ok_counts[0] > 0
    assert out["windows"][2]["valid?"] is True and ok_counts[2] > 0
    # the lossy window: the fault bit (nothing completed ok), and it
    # did NOT produce a false violation — it reads unknown/True, and
    # the heal left the retained cluster serving window 2
    assert ok_counts[1] == 0
    assert out["windows"][1]["valid?"] in (True, "unknown")


def test_soak_net_fault_requires_local_db():
    from jepsen_etcd_tpu.runner.test_runner import run_soak

    with pytest.raises(ValueError, match="proxy plane"):
        run_soak(dict(workload="register", client_type="http",
                      db_mode="live", soak_windows=1,
                      soak_net_faults=["latency"]))


def test_soak_refuses_sim_clients():
    from jepsen_etcd_tpu.runner.test_runner import run_soak

    with pytest.raises(ValueError, match="long-lived live cluster"):
        run_soak(dict(workload="register", client_type="direct",
                      soak_windows=1))
