"""Set-full checker unit tests + set workload end-to-end (SURVEY §7 step 8;
reference semantics at set.clj and the library set-full checker)."""

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers.set_full import SetFull
from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.test_runner import run_test


def H(*ops):
    return History([Op(o) for o in ops])


def add(p, x):
    return ({"type": "invoke", "process": p, "f": "add", "value": x},
            {"type": "ok", "process": p, "f": "add", "value": x})


def add_info(p, x):
    return ({"type": "invoke", "process": p, "f": "add", "value": x},
            {"type": "info", "process": p, "f": "add", "value": x})


def read(p, xs):
    return ({"type": "invoke", "process": p, "f": "read", "value": None},
            {"type": "ok", "process": p, "f": "read", "value": list(xs)})


def flat(*pairs):
    return [o for pair in pairs for o in pair]


def test_stable_elements_valid():
    h = H(*flat(add(0, 1), add(0, 2), read(1, [1, 2]), read(1, [1, 2])))
    r = SetFull(linearizable=True).check({}, h)
    assert r["valid?"] is True
    assert r["stable-count"] == 2
    assert r["lost-count"] == 0


def test_lost_element_invalid():
    # 2 is confirmed added, then vanishes from all later reads
    h = H(*flat(add(0, 1), add(0, 2), read(1, [1, 2]), read(1, [1])))
    r = SetFull().check({}, h)
    assert r["valid?"] is False
    assert r["lost"] == [2]


def test_stale_read_only_fails_linearizable():
    # 2 known at its add :ok, missing from the next read, back in the last:
    # stale (flicker), illegal only in linearizable mode
    h = H(*flat(add(0, 1), add(0, 2), read(1, [1]), read(1, [1, 2])))
    assert SetFull(linearizable=True).check({}, h)["valid?"] is False
    r = SetFull(linearizable=False).check({}, h)
    assert r["valid?"] is True
    assert r["stale"] == [2]
    assert r["worst-stale"][0]["element"] == 2


def test_info_add_never_observed_ok():
    # indefinite add that never shows up: not lost, just unknown
    h = H(*flat(add(0, 1), add_info(1, 9), read(2, [1]), read(2, [1])))
    r = SetFull(linearizable=True).check({}, h)
    assert r["valid?"] is True
    assert r["unknown-count"] == 1


def test_info_add_observed_then_lost_invalid():
    # indefinite add observed by a read (=> it happened), then gone
    h = H(*flat(add(0, 1), add_info(1, 9), read(2, [1, 9]), read(2, [1])))
    r = SetFull().check({}, h)
    assert r["valid?"] is False
    assert r["lost"] == [9]


def test_never_read_is_not_failure():
    h = H(*flat(add(0, 1)))
    r = SetFull(linearizable=True).check({}, h)
    assert r["valid?"] == "unknown"  # no reads: no information
    h2 = H(*flat(read(1, []), add(0, 1)))
    r2 = SetFull(linearizable=True).check({}, h2)
    assert r2["never-read-count"] == 1


def test_duplicate_read_values_invalid():
    h = H(*flat(add(0, 1), read(1, [1, 1])))
    r = SetFull().check({}, h)
    assert r["valid?"] is False
    assert r["duplicated-count"] == 1


def test_set_workload_e2e(tmp_path):
    out = run_test(etcd_test({
        "workload": "set", "time_limit": 6, "rate": 50,
        "store_base": str(tmp_path), "seed": 11}))
    assert out["valid?"] is True
    wl = out["results"]["workload"]
    assert wl["stable-count"] > 10
    assert wl["lost-count"] == 0


def test_set_workload_serializable_stale_reads(tmp_path):
    # Node-local (serializable) reads can be stale; with a linearizable
    # set-full check this must surface as staleness, not loss.
    out = run_test(etcd_test({
        "workload": "set", "time_limit": 8, "rate": 100,
        "serializable": True, "store_base": str(tmp_path), "seed": 3}))
    wl = out["results"]["workload"]
    assert wl["lost-count"] == 0


# ---------------------------------------------------------------------
# Differential: the columnar analysis (one numpy pass) vs the reference
# per-read sweep — the contract analyze()'s docstring promises. The
# columnar path must produce IDENTICAL result dicts on int-valued
# histories, including every anomaly that forces its exact full-mode
# retry (dups, misses, out-of-order views), and fall back cleanly to
# the reference on non-int values.
# ---------------------------------------------------------------------

import random

import pytest

from jepsen_etcd_tpu.checkers.set_full import (_NonColumnar,
                                               _analyze_columnar,
                                               _analyze_reference,
                                               analyze)


def gen_set_history(rng, n_ops=140, p_stale=0.0, p_dup=0.0, p_lose=0.0,
                    p_shuffle=0.0, p_info=0.08):
    """Concurrent set history: adds + snapshot reads over 6 processes,
    with injectable anomalies — stale snapshot reads, duplicated
    elements, silent loss, out-of-order (shuffled) views."""
    ops, store, snaps = [], [], [[]]
    pend, nxt = {}, 0
    for _ in range(n_ops):
        p = rng.randrange(6)
        if p in pend:
            kind, x = pend.pop(p)
            if kind == "add":
                r = rng.random()
                if r < p_info:
                    ops.append(Op(type="info", process=p, f="add",
                                  value=x, error="timeout"))
                    if rng.random() < 0.5:       # took effect anyway
                        store.append(x)
                        snaps.append(sorted(store))
                elif r < p_info + 0.06:
                    ops.append(Op(type="fail", process=p, f="add",
                                  value=x))
                else:
                    ops.append(Op(type="ok", process=p, f="add",
                                  value=x))
                    store.append(x)
                    if p_lose and store and rng.random() < p_lose:
                        store.pop(rng.randrange(len(store)))
                    snaps.append(sorted(store))
            else:
                view = list(snaps[-1])
                if p_stale and len(snaps) > 1 and rng.random() < p_stale:
                    view = list(snaps[rng.randrange(len(snaps))])
                if p_dup and view and rng.random() < p_dup:
                    view.append(view[rng.randrange(len(view))])
                if p_shuffle and rng.random() < p_shuffle:
                    rng.shuffle(view)
                ops.append(Op(type="ok", process=p, f="read",
                              value=view))
        elif rng.random() < 0.55:
            x = nxt
            nxt += 1
            ops.append(Op(type="invoke", process=p, f="add", value=x))
            pend[p] = ("add", x)
        else:
            ops.append(Op(type="invoke", process=p, f="read",
                          value=None))
            pend[p] = ("read", None)
    for p, (kind, x) in pend.items():   # stragglers stay indefinite
        ops.append(Op(type="info", process=p, f=kind, value=x,
                      error="never-returned"))
    return History(ops)


@pytest.mark.parametrize("cfg", [
    dict(),                              # clean growing set
    dict(p_stale=0.2),                   # flickering reads
    dict(p_dup=0.15),                    # duplicated elements
    dict(p_lose=0.1),                    # silent loss
    dict(p_shuffle=0.3),                 # out-of-order views
    dict(p_stale=0.1, p_dup=0.05, p_lose=0.05, p_shuffle=0.1),
])
def test_columnar_matches_reference_fuzz(cfg):
    rng = random.Random(42 + len(cfg))
    for trial in range(6):
        h = gen_set_history(rng, **cfg)
        assert _analyze_columnar(h) == _analyze_reference(h), (cfg, trial)


def test_columnar_empty_and_read_only():
    h0 = H()
    assert _analyze_columnar(h0) == _analyze_reference(h0)
    h1 = H(*flat(read(0, [])))
    assert _analyze_columnar(h1) == _analyze_reference(h1)


def test_columnar_fixture_equivalence():
    """Every hand-written fixture above, both analysis paths."""
    fixtures = [
        H(*flat(add(0, 1), add(0, 2), read(1, [1, 2]), read(1, [1, 2]))),
        H(*flat(add(0, 1), add(0, 2), read(1, [1, 2]), read(1, [1]))),
        H(*flat(add(0, 1), add(0, 2), read(1, [1]), read(1, [1, 2]))),
        H(*flat(add(0, 1), add_info(1, 9), read(2, [1]), read(2, [1]))),
        H(*flat(add(0, 1), add_info(1, 9), read(2, [1, 9]), read(2, [1]))),
        H(*flat(read(1, []), add(0, 1))),
        H(*flat(add(0, 1), read(1, [1, 1]))),
    ]
    for i, h in enumerate(fixtures):
        assert _analyze_columnar(h) == _analyze_reference(h), i


def test_non_int_values_fall_back_to_reference():
    h = H(*flat(
        ({"type": "invoke", "process": 0, "f": "add", "value": "a"},
         {"type": "ok", "process": 0, "f": "add", "value": "a"}),
        ({"type": "invoke", "process": 1, "f": "read", "value": None},
         {"type": "ok", "process": 1, "f": "read", "value": ["a"]})))
    with pytest.raises(_NonColumnar):
        _analyze_columnar(h)
    assert analyze(h) == _analyze_reference(h)
    assert SetFull().check({}, h)["valid?"] is True
