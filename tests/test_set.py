"""Set-full checker unit tests + set workload end-to-end (SURVEY §7 step 8;
reference semantics at set.clj and the library set-full checker)."""

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers.set_full import SetFull
from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.test_runner import run_test


def H(*ops):
    return History([Op(o) for o in ops])


def add(p, x):
    return ({"type": "invoke", "process": p, "f": "add", "value": x},
            {"type": "ok", "process": p, "f": "add", "value": x})


def add_info(p, x):
    return ({"type": "invoke", "process": p, "f": "add", "value": x},
            {"type": "info", "process": p, "f": "add", "value": x})


def read(p, xs):
    return ({"type": "invoke", "process": p, "f": "read", "value": None},
            {"type": "ok", "process": p, "f": "read", "value": list(xs)})


def flat(*pairs):
    return [o for pair in pairs for o in pair]


def test_stable_elements_valid():
    h = H(*flat(add(0, 1), add(0, 2), read(1, [1, 2]), read(1, [1, 2])))
    r = SetFull(linearizable=True).check({}, h)
    assert r["valid?"] is True
    assert r["stable-count"] == 2
    assert r["lost-count"] == 0


def test_lost_element_invalid():
    # 2 is confirmed added, then vanishes from all later reads
    h = H(*flat(add(0, 1), add(0, 2), read(1, [1, 2]), read(1, [1])))
    r = SetFull().check({}, h)
    assert r["valid?"] is False
    assert r["lost"] == [2]


def test_stale_read_only_fails_linearizable():
    # 2 known at its add :ok, missing from the next read, back in the last:
    # stale (flicker), illegal only in linearizable mode
    h = H(*flat(add(0, 1), add(0, 2), read(1, [1]), read(1, [1, 2])))
    assert SetFull(linearizable=True).check({}, h)["valid?"] is False
    r = SetFull(linearizable=False).check({}, h)
    assert r["valid?"] is True
    assert r["stale"] == [2]
    assert r["worst-stale"][0]["element"] == 2


def test_info_add_never_observed_ok():
    # indefinite add that never shows up: not lost, just unknown
    h = H(*flat(add(0, 1), add_info(1, 9), read(2, [1]), read(2, [1])))
    r = SetFull(linearizable=True).check({}, h)
    assert r["valid?"] is True
    assert r["unknown-count"] == 1


def test_info_add_observed_then_lost_invalid():
    # indefinite add observed by a read (=> it happened), then gone
    h = H(*flat(add(0, 1), add_info(1, 9), read(2, [1, 9]), read(2, [1])))
    r = SetFull().check({}, h)
    assert r["valid?"] is False
    assert r["lost"] == [9]


def test_never_read_is_not_failure():
    h = H(*flat(add(0, 1)))
    r = SetFull(linearizable=True).check({}, h)
    assert r["valid?"] == "unknown"  # no reads: no information
    h2 = H(*flat(read(1, []), add(0, 1)))
    r2 = SetFull(linearizable=True).check({}, h2)
    assert r2["never-read-count"] == 1


def test_duplicate_read_values_invalid():
    h = H(*flat(add(0, 1), read(1, [1, 1])))
    r = SetFull().check({}, h)
    assert r["valid?"] is False
    assert r["duplicated-count"] == 1


def test_set_workload_e2e(tmp_path):
    out = run_test(etcd_test({
        "workload": "set", "time_limit": 6, "rate": 50,
        "store_base": str(tmp_path), "seed": 11}))
    assert out["valid?"] is True
    wl = out["results"]["workload"]
    assert wl["stable-count"] > 10
    assert wl["lost-count"] == 0


def test_set_workload_serializable_stale_reads(tmp_path):
    # Node-local (serializable) reads can be stale; with a linearizable
    # set-full check this must surface as staleness, not loss.
    out = run_test(etcd_test({
        "workload": "set", "time_limit": 8, "rate": 100,
        "serializable": True, "store_base": str(tmp_path), "seed": 3}))
    wl = out["results"]["workload"]
    assert wl["lost-count"] == 0
