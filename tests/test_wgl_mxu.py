"""Differential tests: MXU-compacted wave kernel vs the jnp kernel and
the CPU oracle (ops/wgl_mxu.py). The kernel claims definitive answers
only; every claim must match the reference engines. Off-TPU these run
the kernel in pallas interpret mode — same semantics (the packed
(8,128) planes are dense, so reshape views agree between interpret and
Mosaic layouts)."""

import random

import numpy as np
import pytest

from jepsen_etcd_tpu.checkers import check_history
from jepsen_etcd_tpu.models import VersionedRegister
from jepsen_etcd_tpu.ops import wgl
from jepsen_etcd_tpu.ops import wgl_mxu

from test_wgl import gen_history


def run_both(h):
    p = wgl.pack_register_history(h)
    if not p.ok or not wgl_mxu.supported(p):
        return None
    got = wgl_mxu.check_packed_mxu(p)
    ref = wgl.check_packed(p)
    return got, ref, p


@pytest.mark.parametrize("corrupt", [False, True])
def test_differential_vs_jnp_kernel(corrupt):
    rng = random.Random(4242 if corrupt else 77)
    checked = 0
    for trial in range(60):
        h = gen_history(rng, n_procs=rng.randint(2, 5),
                        n_ops=rng.randint(8, 40), corrupt=corrupt)
        got = run_both(h)
        if got is None:
            continue
        mxu, ref, p = got
        if mxu["valid?"] == "unknown" or ref["valid?"] == "unknown":
            continue
        checked += 1
        assert mxu["valid?"] == ref["valid?"], (
            f"trial {trial}: mxu={mxu} ref={ref['valid?']}\n"
            + h.to_jsonl())
    assert checked >= 40, f"only {checked}/60 comparable"


def test_differential_vs_cpu_oracle():
    rng = random.Random(9)
    for trial in range(30):
        h = gen_history(rng, n_procs=3, n_ops=24,
                        corrupt=(trial % 2 == 1))
        got = run_both(h)
        if got is None:
            continue
        mxu, _, _ = got
        if mxu["valid?"] == "unknown":
            continue
        cpu = check_history(VersionedRegister(), h)
        assert mxu["valid?"] == cpu["valid?"], (mxu, cpu, h.to_jsonl())


def test_device_table_builder_matches_host_packer():
    """The jitted frame builder must be bit-identical to pack_tables —
    the whole device path rests on it."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = random.Random(13)
    checked = 0
    for trial in range(20):
        h = gen_history(rng, n_procs=rng.randint(2, 5),
                        n_ops=rng.randint(8, 60),
                        corrupt=(trial % 3 == 0))
        p = wgl.pack_register_history(h)
        if not p.ok or not wgl_mxu.supported(p):
            continue
        r_pad = max(wgl.bucket(p.R), wgl_mxu.TSUB)
        t_host, s_host = wgl_mxu.pack_tables(p, r_pad)
        i32, u16 = wgl_mxu.pack_perop(p, r_pad)
        build = jax.jit(lambda a, b, rp=r_pad, wk=p.w:
                        wgl_mxu._build_tables_one(jnp, lax, a, b, rp, wk))
        t_dev, s_dev = [np.asarray(x)
                        for x in build(jnp.asarray(i32), jnp.asarray(u16))]
        assert (t_dev == t_host).all(), f"trial {trial}: table mismatch"
        assert (s_dev == s_host).all(), f"trial {trial}: scal mismatch"
        if checked == 0:
            # deep-history branch: past OH_MAX_RPAD the builder swaps
            # the one-hot matmul gather for serial jnp.take — both
            # must stay bit-identical to the host packer
            rp_big = 2 * wgl_mxu.OH_MAX_RPAD[p.w]
            t_h2, s_h2 = wgl_mxu.pack_tables(p, rp_big)
            i2, u2 = wgl_mxu.pack_perop(p, rp_big)
            build2 = jax.jit(lambda a, b, wk=p.w:
                             wgl_mxu._build_tables_one(jnp, lax, a, b,
                                                       rp_big, wk))
            t_d2, s_d2 = [np.asarray(x)
                          for x in build2(jnp.asarray(i2),
                                          jnp.asarray(u2))]
            assert (t_d2 == t_h2).all(), "deep-branch table mismatch"
            assert (s_d2 == s_h2).all(), "deep-branch scal mismatch"
        checked += 1
    assert checked >= 10, f"only {checked}/20 comparable"


def test_w64_differential():
    """High-concurrency histories widen the window to two mask words;
    the w=64 kernel variant must agree with the jnp engine (and the
    CPU oracle through it) on both verdict polarities."""
    rng = random.Random(6464)
    checked = 0
    for trial in range(30):
        # many processes with LONG op spans -> deep overlap -> the
        # undecided window exceeds one mask word
        h = gen_history(rng, n_procs=rng.randint(12, 20),
                        n_ops=rng.randint(60, 120),
                        corrupt=(trial % 2 == 1), dur_scale=20.0)
        p = wgl.pack_register_history(h)
        if not p.ok or p.w != 64 or not wgl_mxu.supported(p):
            continue
        got = wgl_mxu.check_packed_mxu(p)
        if got["valid?"] == "unknown":
            continue
        ref = wgl.check_packed(p)
        if ref["valid?"] == "unknown":
            continue
        checked += 1
        assert got["valid?"] == ref["valid?"], (
            f"trial {trial}: mxu={got} ref={ref['valid?']}\n"
            + h.to_jsonl())
    assert checked >= 5, f"only {checked}/30 w=64 comparable"


def test_batch_matches_singles():
    rng = random.Random(31)
    hs = [gen_history(rng, n_procs=3, n_ops=rng.randint(8, 40),
                      corrupt=(i % 4 == 0)) for i in range(12)]
    packs = [wgl.pack_register_history(h) for h in hs]
    outs = wgl_mxu.check_packed_batch_mxu(packs)
    if outs is None:
        pytest.skip("no supported packs in sample")
    for p, out in zip(packs, outs):
        if out is None:
            assert not wgl_mxu.supported(p)
            continue
        single = wgl_mxu.check_packed_mxu(p)
        assert out["valid?"] == single["valid?"], (out, single)


def test_known_good_and_bad_fixtures():
    def H(*ops):
        from jepsen_etcd_tpu.core.op import Op
        from jepsen_etcd_tpu.core.history import History
        out = []
        for i, o in enumerate(ops):
            o = Op(o)
            o["index"] = i
            o.setdefault("time", i)
            out.append(o)
        return History(out)

    def inv(p, f, v):
        return {"type": "invoke", "process": p, "f": f, "value": v}

    def ok(p, f, v):
        return {"type": "ok", "process": p, "f": f, "value": v}

    good = H(inv(0, "write", [None, "a"]), ok(0, "write", [None, "a"]),
             inv(0, "read", [None, None]), ok(0, "read", [None, "a"]))
    bad = H(inv(0, "write", [None, "a"]), ok(0, "write", [None, "a"]),
            inv(0, "read", [None, None]), ok(0, "read", [None, "zzz"]))
    pg = wgl.pack_register_history(good)
    pb = wgl.pack_register_history(bad)
    assert wgl_mxu.check_packed_mxu(pg)["valid?"] is True
    assert wgl_mxu.check_packed_mxu(pb)["valid?"] is False


def test_unsupported_shapes_return_none():
    p = wgl.Packed(ok=False, reason="nope")
    assert wgl_mxu.check_packed_mxu(p) is None
    assert wgl_mxu.supported(p) is False


def test_batch_shards_over_device_mesh():
    """With >1 visible device the fused batch runs through shard_map
    over the ("key",) mesh: output shards land one per device and the
    verdicts match the CPU oracle (SURVEY §2.3: the production fast
    path's key axis is mesh-sharded)."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < 2:
        import pytest
        pytest.skip("needs a multi-device mesh")
    rng = random.Random(7)
    packs, hs = [], []
    while len(packs) < 2 * n_dev:
        h = gen_history(rng, n_procs=3, n_ops=30)
        p = wgl.pack_register_history(h)
        if p.ok and wgl_mxu.supported(p):
            packs.append(p)
            hs.append(h)
    launched = wgl_mxu.launch_packed_batch_mxu(packs)
    outs = [None] * len(packs)
    wgl_mxu.collect_packed_batch_mxu(launched, outs)
    assert max(len(dev.sharding.device_set)
               for _, dev, _ in launched) == n_dev
    for h, out in zip(hs, outs):
        assert out is not None and out["engine"] == "mxu-wave"
        cpu = check_history(VersionedRegister(), h)
        assert out["valid?"] == cpu["valid?"], (out, cpu, h.to_jsonl())


def test_w128_differential():
    """Very-high-overlap histories widen the window to four mask
    words; the w=128 kernel variant must agree with the jnp engine on
    both verdict polarities (VERDICT r4 #6)."""
    rng = random.Random(128128)
    checked = 0
    for trial in range(40):
        h = gen_history(rng, n_procs=rng.randint(26, 40),
                        n_ops=rng.randint(120, 220),
                        corrupt=(trial % 2 == 1), dur_scale=60.0)
        p = wgl.pack_register_history(h)
        if not p.ok or p.w != 128 or not wgl_mxu.supported(p):
            continue
        got = wgl_mxu.check_packed_mxu(p)
        if got["valid?"] == "unknown":
            continue
        ref = wgl.check_packed(p)
        if ref["valid?"] == "unknown":
            continue
        checked += 1
        assert got["valid?"] == ref["valid?"], (
            f"trial {trial}: mxu={got} ref={ref['valid?']}\n"
            + h.to_jsonl())
    assert checked >= 3, f"only {checked}/40 w=128 comparable"
