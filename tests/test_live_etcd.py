"""Real-etcd e2e: the local control plane driving an actual `etcd`
binary. Gated: every test here is @pytest.mark.live and depends on the
`etcd_binary` fixture (tests/conftest.py), which skips with a clear
reason when no etcd is on PATH — so the hermetic CI image runs zero of
these, and a box with etcd installed runs all of them with no
configuration.

The fake-binary twin of each path lives in test_local_db.py; this file
proves the same control plane drives the real thing: real raft
readiness, real member API, real persistence, real gRPC."""

import json
import os

import pytest

from jepsen_etcd_tpu.runner.sim import set_current_loop
from jepsen_etcd_tpu.runner.wall import WallLoop

pytestmark = pytest.mark.live

NODES = ["n1", "n2", "n3"]


@pytest.fixture()
def wall_loop():
    loop = WallLoop()
    set_current_loop(loop)
    yield loop
    set_current_loop(None)
    loop.shutdown()


@pytest.fixture()
def real_cluster(etcd_binary, wall_loop, tmp_path):
    """A real 3-node etcd cluster on loopback; zero leaks after."""
    from jepsen_etcd_tpu.db.local import LocalDb
    db = LocalDb({"etcd_binary": [etcd_binary],
                  "etcd_data_dir": str(tmp_path / "data"),
                  "client_type": "http",
                  "nodes": list(NODES)})
    test = {"nodes": list(NODES), "client_type": "http",
            "db_mode": "local", "db": db}
    wall_loop.run_coro(db.setup(test))
    try:
        yield wall_loop, db, test
    finally:
        db.stop_all()
        assert db.leaked_pids() == []


def test_real_cluster_replicates_and_elects(real_cluster):
    """Write on one node, read on another: real replication — the thing
    the fake stub documents it cannot do."""
    loop, db, test = real_cluster

    async def story():
        c1 = db._client(test, "n1")
        c2 = db._client(test, "n2")
        try:
            await c1.put("replicated", 7)
            return await c2.get("replicated")
        finally:
            c1.close()
            c2.close()

    got = loop.run_coro(story())
    assert got is not None and got["value"] == 7
    prim = loop.run_coro(db.primaries(test))
    assert len(prim) == 1 and prim[0] in NODES


def test_real_kill_majority_and_recover(real_cluster):
    loop, db, test = real_cluster

    async def story():
        c = db._client(test, "n1")
        try:
            await c.put("pre-fault", 1)
        finally:
            c.close()
        db.kill(test, "n2")
        db.kill(test, "n3")
        db.start(test, "n2")
        db.start(test, "n3")
        for node in NODES:
            await db._await_node_ready(test, node)
        c = db._client(test, "n3")
        try:
            return await c.get("pre-fault")
        finally:
            c.close()

    got = loop.run_coro(story())
    assert got is not None and got["value"] == 1


def test_real_member_grow_shrink(real_cluster):
    loop, db, test = real_cluster
    new = loop.run_coro(db.grow(test))
    assert new in db.members and len(db.members) == 4
    victim = loop.run_coro(db.shrink(test))
    assert victim not in db.members and len(db.members) == 3


def test_real_grpc_client_smoke(etcd_binary, wall_loop, tmp_path):
    """The native-gRPC adapter against a real etcd: put/get/txn/status
    over the reference's actual wire protocol."""
    pytest.importorskip("grpc")
    from jepsen_etcd_tpu.db.local import LocalDb
    db = LocalDb({"etcd_binary": [etcd_binary],
                  "etcd_data_dir": str(tmp_path / "data"),
                  "client_type": "grpc",
                  "nodes": ["n1"]})
    test = {"nodes": ["n1"], "client_type": "grpc",
            "db_mode": "local", "db": db}
    wall_loop.run_coro(db.setup(test))
    try:
        async def story():
            c = db._client(test, "n1")
            try:
                await c.put("g", {"nested": [1, 2]})
                got = await c.get("g")
                st = await c.status()
                return got, st
            finally:
                c.close()

        got, st = wall_loop.run_coro(story())
        assert got["value"] == {"nested": [1, 2]}
        assert st["leader"]
    finally:
        db.stop_all()
        assert db.leaked_pids() == []


def test_real_faulted_register_run(etcd_binary, tmp_path):
    """Full checker-stack run with kill+pause nemeses against real etcd
    — the reference's headline scenario (etcd.clj:246-257) without SSH
    or containers."""
    from jepsen_etcd_tpu.cli import main
    rc = main(["test", "-w", "register", "--client-type", "http",
               "--db", "local", "--etcd-binary", etcd_binary,
               "--etcd-data-dir", str(tmp_path / "cluster"),
               "--nodes", "n1,n2,n3", "--nemesis", "kill,pause",
               "--nemesis-interval", "3", "--time-limit", "15",
               "-r", "25", "--store", str(tmp_path / "store")])
    run_dirs = []
    for root, dirs, files in os.walk(tmp_path / "store"):
        if "results.json" in files:
            run_dirs.append(root)
    assert len(run_dirs) == 1
    results = json.load(open(os.path.join(run_dirs[0], "results.json")))
    assert rc == 0, f"run invalid: {json.dumps(results)[:2000]}"
