"""The live-etcd run mode, end-to-end through the CLI.

`--client-type http --endpoint URL` must run a standard workload
against a real-protocol etcd endpoint with no test code involved
(etcd.clj:246-257: the reference CLI drives a live cluster). Hermetic:
the endpoint is sut/http_gateway.py speaking the v3 JSON-gateway wire
format over real HTTP on a real port.
"""

import json
import os
import threading

import pytest

from jepsen_etcd_tpu.sut.http_gateway import serve


@pytest.fixture()
def gateway():
    srv, state = serve()
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def test_cli_live_register_run(gateway, tmp_path):
    from jepsen_etcd_tpu.cli import main
    rc = main(["test", "-w", "register", "--client-type", "http",
               "--endpoint", gateway, "--time-limit", "2", "-r", "25",
               "--store", str(tmp_path)])
    assert rc == 0
    # artifacts written like any sim run
    run_dirs = []
    for root, dirs, files in os.walk(tmp_path):
        if "results.json" in files:
            run_dirs.append(root)
    assert len(run_dirs) == 1
    results = json.load(open(os.path.join(run_dirs[0], "results.json")))
    assert results["valid?"] is True
    assert results["workload"]["valid?"] is True
    history = open(os.path.join(run_dirs[0], "history.jsonl")).read()
    assert history.count('"type": "ok"') > 10
    test_json = json.load(open(os.path.join(run_dirs[0], "test.json")))
    assert test_json["client_type"] == "http"
    assert test_json["nodes"] == [gateway]


def test_cli_live_rejects_nemesis(gateway, tmp_path):
    from jepsen_etcd_tpu.cli import main
    with pytest.raises(ValueError, match="no control plane"):
        main(["test", "-w", "register", "--client-type", "http",
              "--endpoint", gateway, "--nemesis", "kill",
              "--time-limit", "2", "--store", str(tmp_path)])


def test_live_db_refuses_faults():
    from jepsen_etcd_tpu.db.live import LiveDb
    from jepsen_etcd_tpu.sut.errors import SimError
    db = LiveDb({})
    for fault in ("start", "kill", "pause", "resume", "wipe"):
        with pytest.raises(SimError, match="unsupported"):
            getattr(db, fault)({}, "http://x")


def test_live_db_primaries_returns_leader_endpoint(gateway):
    """primaries() must return the endpoint whose own member id is the
    reported leader (db.clj:38-52), not merely the highest-term
    answerer."""
    from jepsen_etcd_tpu.db.live import LiveDb
    from jepsen_etcd_tpu.runner.wall import WallLoop
    from jepsen_etcd_tpu.runner.sim import set_current_loop

    db = LiveDb({})
    db.members = {gateway}
    loop = WallLoop()
    set_current_loop(loop)
    try:
        assert loop.run_coro(db.primaries({})) == [gateway]
    finally:
        set_current_loop(None)
        loop.shutdown()


# ---- native-gRPC live mode -------------------------------------------------

@pytest.fixture()
def grpc_gateway():
    grpc = pytest.importorskip("grpc")
    from jepsen_etcd_tpu.sut.grpc_gateway import serve_grpc
    srv, state, port = serve_grpc()
    yield f"http://127.0.0.1:{port}"
    srv.stop(0)


def test_cli_live_register_run_grpc(grpc_gateway, tmp_path):
    """--client-type grpc runs the same workload over native gRPC —
    the reference's wire protocol (client.clj:14-68)."""
    from jepsen_etcd_tpu.cli import main
    rc = main(["test", "-w", "register", "--client-type", "grpc",
               "--endpoint", grpc_gateway, "--time-limit", "2",
               "-r", "25", "--store", str(tmp_path)])
    assert rc == 0
    run_dirs = []
    for root, dirs, files in os.walk(tmp_path):
        if "results.json" in files:
            run_dirs.append(root)
    assert len(run_dirs) == 1
    results = json.load(open(os.path.join(run_dirs[0], "results.json")))
    assert results["valid?"] is True
    assert results["workload"]["valid?"] is True
    history = open(os.path.join(run_dirs[0], "history.jsonl")).read()
    assert history.count('"type": "ok"') > 10
    test_json = json.load(open(os.path.join(run_dirs[0], "test.json")))
    assert test_json["client_type"] == "grpc"
    assert test_json["nodes"] == [grpc_gateway]
