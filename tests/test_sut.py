import pytest

from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop, sleep, SECOND
from jepsen_etcd_tpu.sut import Cluster, ClusterConfig, SimError, Txn, Store
from jepsen_etcd_tpu.sut.cluster import MS

NODES = ["n1", "n2", "n3", "n4", "n5"]


@pytest.fixture
def sim():
    loop = SimLoop(seed=7)
    set_current_loop(loop)
    cluster = Cluster(loop, NODES)
    cluster.launch()
    yield loop, cluster
    cluster.shutdown()
    set_current_loop(None)


def run(loop, coro):
    return loop.run_coro(coro)


async def await_leader(cluster, timeout_s=10):
    from jepsen_etcd_tpu.runner.sim import current_loop
    loop = current_loop()
    deadline = loop.now + timeout_s * SECOND
    while loop.now < deadline:
        leaders = [n for n in cluster.nodes.values()
                   if n.alive and n.role == "leader" and not n.removed]
        if leaders:
            return leaders[0]
        await sleep(100 * MS)
    raise AssertionError("no leader elected")


def put_txn(k, v):
    return Txn((), (("put", k, v, 0),), ())


def test_election_and_write(sim):
    loop, cluster = sim

    async def main():
        leader = await await_leader(cluster)
        res = await cluster.kv_txn("n1", put_txn("foo", 42))
        assert res["succeeded"]
        assert res["revision"] == 2  # first write -> revision 2
        out = await cluster.kv_read("n3", "foo")
        assert out["kv"]["value"] == 42
        assert out["kv"]["version"] == 1
        res2 = await cluster.kv_txn("n2", put_txn("foo", 43))
        out2 = await cluster.kv_read("n5", "foo")
        assert out2["kv"]["version"] == 2
        assert out2["kv"]["mod-revision"] == 3
        assert out2["kv"]["create-revision"] == 2
        return leader.name

    run(loop, main())


def test_cas_txn_semantics(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        await cluster.kv_txn("n1", put_txn("k", 1))
        # CAS 1->2 succeeds
        r = await cluster.kv_txn("n1", Txn(
            (("=", "k", "value", 1),), (("put", "k", 2, 0),), ()))
        assert r["succeeded"]
        # CAS 1->3 fails (value is 2 now)
        r = await cluster.kv_txn("n1", Txn(
            (("=", "k", "value", 1),), (("put", "k", 3, 0),), ()))
        assert not r["succeeded"]
        out = await cluster.kv_read("n2", "k")
        assert out["kv"]["value"] == 2
        # absent-key guard: mod_revision of missing key compares as 0
        r = await cluster.kv_txn("n1", Txn(
            (("<", "missing", "mod_revision", 100),),
            (("put", "probe", 1, 0),), ()))
        assert r["succeeded"]

    run(loop, main())


def test_leader_kill_reelection(sim):
    loop, cluster = sim

    async def main():
        leader = await await_leader(cluster)
        await cluster.kv_txn("n1", put_txn("a", 1))
        cluster.kill_node(leader.name)
        new_leader = None
        deadline = loop.now + 15 * SECOND
        while loop.now < deadline:
            ls = [n for n in cluster.nodes.values()
                  if n.alive and n.role == "leader"]
            if ls and ls[0].name != leader.name:
                new_leader = ls[0]
                break
            await sleep(100 * MS)
        assert new_leader is not None, "no re-election"
        # data survives
        alive_node = new_leader.name
        out = await cluster.kv_read(alive_node, "a")
        assert out["kv"]["value"] == 1
        # restart old leader; it rejoins and catches up
        cluster.start_node(leader.name)
        await sleep(3 * SECOND)
        out = await cluster.kv_read(leader.name, "a", serializable=True)
        assert out["kv"] is not None and out["kv"]["value"] == 1

    run(loop, main())


def test_partition_minority_unavailable(sim):
    loop, cluster = sim

    async def main():
        leader = await await_leader(cluster)
        others = [n for n in NODES if n != leader.name]
        # isolate the leader with one follower (minority)
        minority = [leader.name, others[0]]
        majority = others[1:]
        cluster.partition([minority, majority])
        # majority elects a new leader
        await sleep(5 * SECOND)
        maj_leaders = [n for n in cluster.nodes.values()
                       if n.role == "leader" and n.name in majority]
        assert maj_leaders, "majority failed to elect"
        # writes via majority work
        res = await cluster.kv_txn(majority[0], put_txn("p", 9))
        assert res["succeeded"]
        # old leader stepped down (check-quorum)
        assert cluster.nodes[leader.name].role != "leader"
        # a linearizable op via the minority hangs -> timeout at client level
        from jepsen_etcd_tpu.runner.sim import wait_for, current_loop
        t = current_loop().spawn(cluster.kv_txn(minority[0], put_txn("p", 10)))
        with pytest.raises(TimeoutError):
            await wait_for(t, 5 * SECOND)
        # serializable read on minority is stale but served
        out = await cluster.kv_read(minority[0], "p", serializable=True)
        assert out["kv"] is None  # never saw the majority write
        cluster.heal_partition()
        await sleep(3 * SECOND)
        out = await cluster.kv_read(minority[0], "p", serializable=True)
        assert out["kv"] is not None and out["kv"]["value"] == 9

    run(loop, main())


def test_lease_expiry_deletes_keys(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        lid = await cluster.lease_grant("n1", 2 * SECOND)
        await cluster.kv_txn("n1", Txn((), (("put", "locked", 5, lid),), ()))
        out = await cluster.kv_read("n2", "locked")
        assert out["kv"] is not None
        # no keepalive: expires after ~2s
        await sleep(4 * SECOND)
        out = await cluster.kv_read("n2", "locked")
        assert out["kv"] is None
        # keepalive path
        lid2 = await cluster.lease_grant("n1", 2 * SECOND)
        await cluster.kv_txn("n1", Txn((), (("put", "ka", 6, lid2),), ()))
        for _ in range(6):
            await sleep(1 * SECOND)
            await cluster.lease_keepalive("n1", lid2)
        out = await cluster.kv_read("n2", "ka")
        assert out["kv"] is not None

    run(loop, main())


def test_lock_mutual_exclusion(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        lid1 = await cluster.lease_grant("n1", 30 * SECOND)
        lid2 = await cluster.lease_grant("n2", 30 * SECOND)
        key1 = await cluster.lock("n1", "mylock", lid1)
        # second locker blocks
        t2 = loop.spawn(cluster.lock("n2", "mylock", lid2))
        await sleep(2 * SECOND)
        assert not t2.done
        await cluster.unlock("n1", key1)
        key2 = await t2
        assert key2 != key1
        # unlock of a non-held key errors
        with pytest.raises(SimError) as ei:
            await cluster.unlock("n1", key1)
        assert ei.value.type == "not-held"
        await cluster.unlock("n2", key2)

    run(loop, main())


def test_watch_stream_order(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        got = []
        w = cluster.watch("n3", "w", 1, lambda evs: got.extend(evs),
                          lambda err: got.append(("error", err)))
        for i in range(5):
            await cluster.kv_txn("n1", put_txn("w", i))
        await sleep(1 * SECOND)
        vals = [e.kv["value"] for e in got if not isinstance(e, tuple)]
        assert vals == [0, 1, 2, 3, 4]
        revs = [e.revision for e in got]
        assert revs == sorted(revs)
        w.cancel()

    run(loop, main())


def test_wal_corruption_panics_on_restart(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        for i in range(10):
            await cluster.kv_txn("n1", put_txn(f"k{i}", i))
        victim = "n5"
        cluster.kill_node(victim)
        cluster.corrupt_file(victim, which="wal", mode="bitflip",
                             probability=1e-2)
        with pytest.raises(SimError) as ei:
            cluster.start_node(victim)
        assert ei.value.type == "corrupt"
        assert any("panic" in line for line in
                   cluster.nodes[victim].etcd_log)

    run(loop, main())


def test_lazyfs_majority_kill_loses_data():
    """The etcd+lazyfs data-loss scenario: unfsynced writes on a killed
    majority vanish; an acknowledged write can be lost (db.clj:264-267)."""
    loop = SimLoop(seed=11)
    set_current_loop(loop)
    cfg = ClusterConfig(lazyfs=True, unsafe_no_fsync=True)
    cluster = Cluster(loop, NODES, cfg)
    cluster.launch()

    async def main():
        await await_leader(cluster)
        res = await cluster.kv_txn("n1", put_txn("precious", 1))
        assert res["succeeded"]  # acknowledged!
        # kill everyone; unfsynced WAL tail is lost everywhere
        for n in NODES:
            cluster.kill_node(n, lose_unfsynced=True)
        for n in NODES:
            cluster.start_node(n)
        await await_leader(cluster)
        out = await cluster.kv_read("n1", "precious")
        # the acknowledged write is GONE - checkers must catch this
        assert out["kv"] is None

    loop.run_coro(main())
    cluster.shutdown()
    set_current_loop(None)


def test_snapshot_and_catchup(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        victim = "n4"
        cluster.kill_node(victim)
        # push well past snapshot_count (100) so the log prefix is dropped
        for i in range(150):
            await cluster.kv_txn("n1", put_txn(f"s{i % 7}", i))
        cluster.start_node(victim)
        await sleep(5 * SECOND)
        n = cluster.nodes[victim]
        out = await cluster.kv_read(victim, "s0", serializable=True)
        assert out["kv"] is not None
        # all live nodes converge to the same fingerprint
        await sleep(2 * SECOND)
        rep = cluster.consistency_report()
        fps = {v["fingerprint"] for k, v in rep.items()
               if cluster.nodes[k].alive}
        assert len(fps) == 1, rep

    run(loop, main())


def test_membership_add_remove(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        await cluster.kv_txn("n1", put_txn("m", 1))
        # remove n5 (the leader may remove itself; allow re-election time)
        await cluster.member_remove("n1", "n5")
        deadline = loop.now + 15 * SECOND
        names = None
        while loop.now < deadline:
            await sleep(500 * MS)
            try:
                ms = await cluster.member_list("n1")
            except SimError:
                continue
            names = [m["name"] for m in ms]
            if "n5" not in names:
                break
        assert names is not None and "n5" not in names and len(names) == 4
        # member maps carry stable etcd-style ids + URL scheme
        assert all(isinstance(m["id"], int) and
                   m["peer-urls"] == [f"http://{m['name']}:2380"]
                   for m in ms)
        members = names
        # ops against the removed node fail definitely
        with pytest.raises(SimError) as ei:
            await cluster.kv_txn("n5", put_txn("m", 2))
        assert ei.value.type == "raft-stopped"
        # add a brand new node n6
        await cluster.member_add("n1", "n6")
        cluster.start_node("n6", fresh=True,
                           initial_membership=members + ["n6"])
        await sleep(5 * SECOND)
        out = await cluster.kv_read("n6", "m", serializable=True)
        assert out["kv"] is not None and out["kv"]["value"] == 1

    run(loop, main())


def test_compaction_and_watch_from_compacted(sim):
    loop, cluster = sim

    async def main():
        await await_leader(cluster)
        for i in range(20):
            await cluster.kv_txn("n1", put_txn("c", i))
        await cluster.compact("n1", 15, physical=True)
        errors = []
        cluster.watch("n1", "c", 2, lambda evs: None,
                      lambda err: errors.append(err))
        await sleep(1 * SECOND)
        assert errors and errors[0].type == "compacted"

    run(loop, main())


def test_determinism_cluster():
    def once():
        loop = SimLoop(seed=5)
        set_current_loop(loop)
        cluster = Cluster(loop, NODES)
        cluster.launch()

        async def main():
            await await_leader(cluster)
            outs = []
            for i in range(10):
                r = await cluster.kv_txn("n1", put_txn("d", i))
                outs.append((r["revision"], loop.now))
            return outs

        out = loop.run_coro(main())
        cluster.shutdown()
        set_current_loop(None)
        return out

    assert once() == once()


def test_resumed_stale_leader_cannot_serve_stale_read(sim):
    # Regression: a leader resumed from SIGSTOP after a successor was
    # elected must not serve a linearizable read from its stale store.
    loop, cluster = sim

    async def main():
        leader = await await_leader(cluster)
        await cluster.kv_txn("n1", put_txn("x", 1))
        cluster.pause_node(leader.name)
        # wait for a successor and a new committed write
        deadline = loop.now + 20 * SECOND
        new_leader = None
        while loop.now < deadline:
            ls = [n for n in cluster.nodes.values()
                  if n.alive and not n.paused and n.role == "leader"]
            if ls:
                new_leader = ls[0]
                break
            await sleep(100 * MS)
        assert new_leader is not None
        await cluster.kv_txn(new_leader.name, put_txn("x", 2))
        cluster.resume_node(leader.name)
        # immediately read via the resumed stale leader: must NOT see x=1
        from jepsen_etcd_tpu.runner.sim import wait_for
        try:
            t = loop.spawn(cluster.kv_read(leader.name, "x"))
            out = await wait_for(t, 5 * SECOND)
            assert out["kv"]["value"] == 2, "stale linearizable read!"
        except (SimError, TimeoutError):
            pass  # leader-changed / timeout are both linearizable outcomes

    run(loop, main())


def test_election_seed_sweep():
    """Message-level elections (delayed vote request/response RPCs):
    across many seeds — including leader kills mid-campaign and
    partitions — elections must converge, at most one leader per term
    must exist, and committed writes must survive (VERDICT r1 item 4)."""
    for seed in range(12):
        loop = SimLoop(seed=1000 + seed)
        set_current_loop(loop)
        cluster = Cluster(loop, NODES)
        cluster.launch()
        terms_with_leader = {}

        async def main():
            leader = await await_leader(cluster)
            await cluster.kv_txn(leader.name, put_txn("k", seed))
            # churn: kill the leader twice, partition once
            for round_ in range(2):
                victim = [n for n in cluster.nodes.values()
                          if n.alive and n.role == "leader"]
                if victim:
                    cluster.kill_node(victim[0].name)
                leader = await await_leader(cluster, timeout_s=30)
                await cluster.kv_txn(leader.name,
                                     put_txn(f"k{round_}", round_))
            # heal everything
            for n in NODES:
                if not cluster.nodes[n].alive:
                    cluster.start_node(n)
            leader = await await_leader(cluster, timeout_s=30)
            out = await cluster.kv_read(leader.name, "k")
            assert out["kv"]["value"] == seed
            # single-leader-per-term invariant across the live cluster
            for n in cluster.nodes.values():
                if n.alive and n.role == "leader":
                    other = terms_with_leader.get(n.term)
                    assert other in (None, n.name), \
                        f"two leaders in term {n.term}: {other}, {n.name}"
                    terms_with_leader[n.term] = n.name

        loop.run_coro(main())
        cluster.shutdown()
        set_current_loop(None)


def test_split_vote_possible():
    """With message-delayed votes, simultaneous campaigns can split the
    vote; the cluster must still converge afterwards. Verify campaigns
    actually interleave (more than one campaign before a winner) for at
    least one seed — atomic elections could never produce this."""
    saw_competing_campaigns = False
    for seed in range(20):
        loop = SimLoop(seed=seed)
        set_current_loop(loop)
        cluster = Cluster(loop, NODES)
        cluster.launch()

        async def main():
            nonlocal saw_competing_campaigns
            # force every node's election deadline to (almost) the same
            # instant so several campaigns launch in the same tick window
            for n in cluster.nodes.values():
                n.election_deadline = loop.now + 1
            await sleep(60 * MS)
            candidates = [n for n in cluster.nodes.values()
                          if n.role == "candidate"]
            if len(candidates) >= 2:
                saw_competing_campaigns = True
            await await_leader(cluster, timeout_s=30)

        loop.run_coro(main())
        cluster.shutdown()
        set_current_loop(None)
    assert saw_competing_campaigns, \
        "no seed produced competing campaigns — elections look atomic"


def test_fsync_mode_survives_lose_unfsynced():
    """With unsafe_no_fsync=False every append is fsynced (durable WAL
    mirrors the live one, incl. after truncation rewrites), so killing
    all nodes losing unfsynced writes loses nothing."""
    loop = SimLoop(seed=4)
    set_current_loop(loop)
    try:
        cluster = Cluster(loop, ["n1", "n2", "n3"],
                          ClusterConfig(unsafe_no_fsync=False))
        cluster.launch()

        async def main():
            await await_leader(cluster)
            for i in range(30):
                await cluster.kv_txn("n1", put_txn(f"k{i}", i))
            await sleep(500 * MS)
            for n in list(cluster.nodes):
                cluster.kill_node(n, lose_unfsynced=True)
            for n in list(cluster.nodes):
                cluster.start_node(n)
            await await_leader(cluster, timeout_s=30)
            for i in range(30):
                out = await cluster.kv_read("n1", f"k{i}")
                assert out["kv"] is not None and out["kv"]["value"] == i, i

        loop.run_coro(main())
        cluster.shutdown()
    finally:
        set_current_loop(None)


# ---- RecordFile (lazy byte materialization, wal.py) -----------------------

def test_record_file_obj_mode_roundtrip():
    """OBJ mode: appends/fsync/lose_unfsynced never touch bytes."""
    from jepsen_etcd_tpu.sut.wal import RecordFile
    f = RecordFile()
    f.append(("a", 1), sync=True)
    f.append(("b", [1, 2, 3]), sync=True)
    f.append(("c", 3), sync=False)          # unfsynced tail
    assert not f.byte_mode
    items, err = f.read()
    assert err is None and [i[0] for i in items] == ["a", "b", "c"]
    f.lose_unfsynced()
    items, err = f.read()
    assert err is None and [i[0] for i in items] == ["a", "b"]
    assert f.size > 0


def test_record_file_corruption_materializes_and_breaks_crc():
    """Corruption flips to BYTES mode; a bitflipped record fails CRC at
    replay exactly as the framed encoding dictates."""
    import random
    from jepsen_etcd_tpu.sut.wal import RecordFile
    f = RecordFile()
    for i in range(8):
        f.append((i, i * 10), sync=True)
    f.corrupt(random.Random(5), mode="bitflip", probability=0.01)
    assert f.byte_mode
    items, err = f.read()
    # a flip in a payload breaks that record's CRC; a flip in a length
    # field can instead make the tail read torn — damaged either way
    assert err in ("crc-mismatch", "torn-record")
    assert len(items) < 8
    # wholesale rewrite (recovery re-encode) returns to OBJ mode
    f.set_records(items, sync=True)
    assert not f.byte_mode
    assert f.read() == (items, None)


def test_record_file_truncate_drops_tail_records():
    import random
    from jepsen_etcd_tpu.sut.wal import RecordFile
    f = RecordFile()
    for i in range(6):
        f.append((i, "x" * 50), sync=True)
    f.corrupt(random.Random(3), mode="truncate", truncate_bytes=80)
    items, err = f.read()
    assert err == "torn-record"     # mid-write tail is tolerated
    assert 0 < len(items) < 6


def test_record_file_bytes_mode_append_and_lose():
    """After corruption the byte buffer is authoritative: appends frame
    onto it and lose_unfsynced rolls back to the durable bytes."""
    import random
    from jepsen_etcd_tpu.sut.wal import RecordFile
    f = RecordFile()
    f.append((1, "a"), sync=True)
    f.corrupt(random.Random(7), mode="bitflip", probability=0.0)  # no-op flip
    assert f.byte_mode
    f.append((2, "b"), sync=False)
    items, err = f.read()
    assert err is None and len(items) == 2
    f.lose_unfsynced()
    items, err = f.read()
    assert err is None and len(items) == 1


def test_store_clone_events_cow():
    """Clones share the events list; an append on either side breaks the
    sharing without disturbing the other's view."""
    from jepsen_etcd_tpu.sut.store import Store
    s = Store()
    s.apply_txn(Txn((), (("put", "k", 1, 0),), ()))
    snap = s.clone()
    assert snap.events is s.events
    s.apply_txn(Txn((), (("put", "k", 2, 0),), ()))
    assert snap.events is not s.events
    assert len(snap.events) == 1 and len(s.events) == 2


def test_record_file_unsynced_rewrite_preserves_damaged_durable():
    """Corrupt, then an UNSYNCED wholesale rewrite (recovery re-encode
    under --unsafe-no-fsync): the durable view must keep the damaged
    bytes so a later lose-unfsynced crash + replay still sees the
    damage — the rewrite must not launder it into a clean prefix."""
    import random
    from jepsen_etcd_tpu.sut.wal import RecordFile
    f = RecordFile()
    for i in range(8):
        f.append((i, "v" * 40), sync=True)
    f.corrupt(random.Random(2), mode="bitflip", probability=0.02)
    _, err0 = f.read()
    assert err0 is not None
    f.set_records([(0, "clean")], sync=False)   # unsynced rewrite
    assert f.read() == ([(0, "clean")], None)   # current view is clean
    f.lose_unfsynced()                          # crash: back to disk
    _, err1 = f.read()
    assert err1 == err0                         # damage survived


def test_new_leader_read_index_waits_for_own_term_commit(sim):
    """A newly-elected leader must NOT serve linearizable reads until
    its own-term noop commits: its log holds every entry the old
    leader acked (election restriction), but commit KNOWLEDGE travels
    with later appends, so its applied state can lag acked writes.
    Found in-harness by the register checker (r5): a killed leader +
    election churn produced a 2.3 s window of stale linearizable
    reads. etcd refuses ReadIndex until the noop commits; so do we.

    The lagging-leader state is manufactured directly (an acked entry
    in the log, commit knowledge not yet arrived, leadership won) with
    replication suppressed, so both outcomes are deterministic: the
    pre-fix read-index serves the stale value instantly; the fixed one
    refuses until the own-term noop could commit."""
    loop, cluster = sim
    from jepsen_etcd_tpu.sut.cluster import LogEntry

    async def main():
        leader = await await_leader(cluster)
        await cluster.kv_txn("n1", put_txn("k", 1))
        await sleep(1 * SECOND)                    # k=1 settles everywhere
        g = next(n for n in cluster.nodes.values()
                 if n.alive and n.name != leader.name)
        # the predecessor acked k=2: the entry reached g's log (and a
        # majority), but g's commit_index still points at k=1 — the
        # exact state a fresh leader is in before its noop commits
        e = LogEntry(index=g.last_index() + 1, term=leader.term,
                     kind="txn", payload=put_txn("k", 2))
        g.log.append(e)
        g.wal_append(e)
        cluster.kill_node(leader.name)
        g.role = "leader"
        g.term = leader.term + 1    # won the election; noop suppressed
        g.leader_hint = g.name
        read_state = {}

        async def read():
            read_state["out"] = await cluster.kv_read(g.name, "k")

        task = loop.spawn(read())
        await sleep(int(0.5 * SECOND))
        if task.done:
            # if a read was served in the window, it must NOT be stale
            assert read_state["out"]["kv"]["value"] == 2, (
                f"stale linearizable read: {read_state['out']['kv']}")
        else:
            # correctly refusing to serve until the own-term noop
            # commits (replication is suppressed, so it never does)
            task.cancel()

    run(loop, main())
