"""Store rotation: long sweeps must not fill the disk (VERDICT r2 #8)."""

import os

from jepsen_etcd_tpu.runner.store import (make_store_dir, link_latest,
                                          rotate_store)


def _write_run(base, name, kb):
    d = make_store_dir(base, name)
    with open(os.path.join(d, "history.jsonl"), "w") as f:
        f.write("x" * (kb * 1024))
    link_latest(d)
    return d


def test_rotation_removes_oldest_until_under_cap(tmp_path):
    base = str(tmp_path)
    runs = [_write_run(base, "t", 10) for _ in range(6)]  # 60 KiB
    # tighten mtimes so order is deterministic
    for i, d in enumerate(runs):
        os.utime(d, (1000 + i, 1000 + i))
    removed = rotate_store(base, keep_dir=runs[-1], max_bytes=35 * 1024)
    assert removed == runs[:3]
    assert all(not os.path.exists(r) for r in runs[:3])
    assert all(os.path.exists(r) for r in runs[3:])


def test_rotation_never_removes_current_run(tmp_path):
    base = str(tmp_path)
    runs = [_write_run(base, "t", 10) for _ in range(3)]
    for i, d in enumerate(runs):
        os.utime(d, (1000 + i, 1000 + i))
    # cap below even one run: everything but keep_dir goes
    removed = rotate_store(base, keep_dir=runs[0], max_bytes=1024)
    assert runs[0] not in removed
    assert os.path.exists(runs[0])
    assert all(not os.path.exists(r) for r in runs[1:])


def test_rotation_disabled_with_zero_cap(tmp_path):
    base = str(tmp_path)
    runs = [_write_run(base, "t", 10) for _ in range(3)]
    assert rotate_store(base, max_bytes=0) == []
    assert all(os.path.exists(r) for r in runs)


def test_rotation_unlinks_dangling_latest(tmp_path):
    base = str(tmp_path)
    old = _write_run(base, "t", 10)
    os.utime(old, (1000, 1000))
    new = _write_run(base, "u", 10)
    os.utime(new, (2000, 2000))
    rotate_store(base, keep_dir=new, max_bytes=12 * 1024)
    assert not os.path.exists(old)
    t_latest = os.path.join(base, "t", "latest")
    assert not os.path.islink(t_latest) or os.path.exists(t_latest)
    # the surviving test's latest still resolves
    assert os.path.exists(os.path.join(base, "u", "latest"))


def test_new_run_after_rotation_never_reuses_surviving_id(tmp_path):
    """Run ids are max+1, not count: after rotation deletes the oldest
    dirs, a count-derived id would collide with a surviving run and
    silently overwrite its artifacts."""
    base = str(tmp_path)
    runs = [_write_run(base, "t", 10) for _ in range(6)]
    for i, d in enumerate(runs):
        os.utime(d, (1000 + i, 1000 + i))
    rotate_store(base, keep_dir=runs[-1], max_bytes=35 * 1024)
    nxt = make_store_dir(base, "t")
    assert os.path.basename(nxt) == "00006"
    assert nxt not in runs
    assert not os.listdir(nxt)  # fresh dir, nobody's artifacts
