"""The batched fleet generator (simbatch/, ISSUE 13 tentpole): SoA
event-queue semantics (tombstone cancels, epoch drain order, compaction
parity), lockstep engine determinism, born-columnar histories, the
16-seed golden-hash pin, the epoch-v2 vs epoch-v1 verdict-equality
fuzz, and the session-checker stale-read regression.

The golden hashes pin BOTH the epoch-v2 ordering rule and the
``BatchConfig.from_opts`` sizing mapping: an intentional change to
either must bump the generator epoch (the ledger in runner/sim.py)
and re-pin here in the same commit.
"""

import hashlib

import numpy as np
import pytest

from jepsen_etcd_tpu.simbatch import (GEN_EPOCH_V1, GEN_EPOCH_V2,
                                      BatchConfig, BatchHeap, generate,
                                      generate_for_opts, history_sha,
                                      supports)

# ---- heap: tombstones ------------------------------------------------------


def test_tombstone_cancel_skips_entry():
    h = BatchHeap(2, capacity=4, epoch=GEN_EPOCH_V2)
    h.push(10, 0, 1)
    h.push(20, 1, 1)
    h.push(30, 2, 1)
    h.cancel(1)  # lane 1 (t=20) tombstoned in place, both seeds
    assert h.size().tolist() == [2, 2]
    t, _, lanes, has = h.pop_min()
    assert has.all() and t.tolist() == [10, 10]
    t, _, lanes, has = h.pop_min()
    assert has.all() and t.tolist() == [30, 30]
    assert lanes.tolist() == [2, 2]
    _, _, _, has = h.pop_min()
    assert not has.any()


def test_cancel_respects_mask_and_kind():
    h = BatchHeap(2, capacity=4)
    h.push(10, 0, 7)
    h.push(20, 0, 8)  # same lane, different kind
    h.cancel(0, mask=np.array([True, False]), kind=7)
    # seed 0 lost only the kind-7 entry; seed 1 kept both
    assert h.size().tolist() == [1, 2]
    t, kinds, _, has = h.pop_min()
    assert has.all()
    assert t.tolist() == [20, 10] and kinds.tolist() == [8, 7]


# ---- heap: epoch same-instant ordering -------------------------------------


def _same_instant_drain(epoch):
    h = BatchHeap(1, capacity=8, epoch=epoch)
    for lane in (3, 1, 2):  # push order deliberately != lane order
        h.push(100, lane, 0)
    t, kinds, lanes, count = h.pop_same_instant()
    assert t.tolist() == [100] and count.tolist() == [3]
    return lanes[0, :3].tolist()


def test_epoch_rule_same_instant_batch_drain():
    """The declared epoch contract at the heap level: v1 drains ties in
    push order (time, seq); v2 drains them in owning-lane order
    (time, lane, seq)."""
    assert _same_instant_drain(GEN_EPOCH_V1) == [3, 1, 2]
    assert _same_instant_drain(GEN_EPOCH_V2) == [1, 2, 3]


def test_epoch_rule_pop_min_tiebreak():
    for epoch, want in ((GEN_EPOCH_V1, 2), (GEN_EPOCH_V2, 0)):
        h = BatchHeap(1, capacity=4, epoch=epoch)
        h.push(5, 2, 0)
        h.push(5, 0, 0)
        _, _, lanes, has = h.pop_min()
        assert has.all() and lanes.tolist() == [want], epoch


# ---- heap: compaction parity + growth --------------------------------------


def _churn_drain(auto_compact):
    """Pseudo-random push/cancel churn, then a full drain. The returned
    sequence must not depend on when (or whether) compaction ran."""
    h = BatchHeap(3, capacity=2, epoch=GEN_EPOCH_V2,
                  auto_compact=auto_compact)
    rng = np.random.default_rng(42)
    for i in range(24):
        h.push(rng.integers(1, 10_000, 3), int(rng.integers(0, 8)),
               int(rng.integers(0, 3)))
        if i % 3 == 2:
            h.cancel(int(rng.integers(0, 8)),
                     mask=rng.random(3) < 0.7)
    out = []
    while True:
        t, kinds, lanes, has = h.pop_min()
        if not has.any():
            break
        out.append((t[has].tolist(), kinds[has].tolist(),
                    lanes[has].tolist(), has.tolist()))
    return out, h.compactions, h.capacity


def test_compaction_parity_and_geometric_growth():
    compacted, n_compacts, _ = _churn_drain(auto_compact=2)
    lazy, n_lazy, cap = _churn_drain(auto_compact=10 ** 9)
    assert n_compacts > 0, "low threshold must force compaction traffic"
    assert compacted == lazy, \
        "compaction changed drain order (must be drain-order neutral)"
    assert cap > 2, "churn beyond capacity must grow geometrically"


def test_unique_times_fast_path_is_equivalent():
    """unique_times=True skips ordinal bookkeeping; with all-distinct
    times the drain sequence must be identical to the general path."""
    def drain(unique):
        h = BatchHeap(2, capacity=4, epoch=GEN_EPOCH_V2,
                      unique_times=unique)
        rng = np.random.default_rng(9)
        times = rng.permutation(np.arange(1, 13)).reshape(6, 2)
        for i in range(6):
            h.push(times[i], i, i % 3)
        out = []
        while True:
            t, k, l, has = h.pop_min()
            if not has.any():
                break
            out.append((t.tolist(), k.tolist(), l.tolist()))
        return out
    assert drain(False) == drain(True)


# ---- engine: determinism, composition, born-columnar -----------------------


def test_generate_deterministic_and_composition_independent():
    cfg = BatchConfig(workload="register", lanes=4, ops_per_lane=30,
                      rate=500.0)
    g1 = generate(cfg, [3, 5, 7])
    g2 = generate(cfg, [3, 5, 7])
    s1 = [history_sha(h) for h in g1["histories"]]
    assert s1 == [history_sha(h) for h in g2["histories"]]
    # a seed's history is a pure function of (seed, config): which
    # other seeds share the batch must not matter
    solo = generate(cfg, [5])
    assert history_sha(solo["histories"][0]) == s1[1]
    assert g1["epoch"] == GEN_EPOCH_V2


def test_histories_born_columnar():
    g = generate(BatchConfig(lanes=4, ops_per_lane=20), [1, 2])
    assert g["events"] == sum(len(h) for h in g["histories"]) > 0
    for h in g["histories"]:
        assert h._ops is None, \
            "generation materialized op dicts (must be born columnar)"
        assert len(h.columns) == len(h) > 0
        # per-seed times strictly increase: the lane-residue encoding
        # guarantees tie-free drains, so the finished order is total
        assert (np.diff(np.asarray(h.columns.time)) > 0).all()


def test_supports_and_config_validation():
    assert supports("register") and supports("set")
    assert not supports("watch")
    with pytest.raises(ValueError, match="does not support"):
        BatchConfig(workload="watch")


# ---- the 16-seed golden pin ------------------------------------------------

#: the bench/dry batched config (bench.py _dry_gen_batched uses the
#: same shape)
GOLDEN_OPTS = {"workload": "register", "nodes": ["n1", "n2", "n3"],
               "concurrency": 8, "rate": 200.0, "time_limit": 2.0}

GOLDEN_SEED0 = \
    "f994af9bf3d2cb2728c4993bd44a13db92cbc70bc8f42f46bb33291d5e88da69"
GOLDEN_JOINED = \
    "89d9966eabeb0b1fa01943ac93921db260b503c3ec48e56ec830891674f21d69"


def test_golden_hash_16_seed_pin():
    """Epoch-v2 is pinned: these 16 histories must serialize to these
    exact bytes on every platform. If this fails, either a bug slipped
    into the engine, or the ordering/sizing contract changed — the
    latter REQUIRES a new generator epoch (runner/sim.py ledger), not a
    re-pin under epoch-v2."""
    g = generate_for_opts(dict(GOLDEN_OPTS), range(16))
    assert g["epoch"] == GEN_EPOCH_V2
    shas = [history_sha(h) for h in g["histories"]]
    assert shas[0] == GOLDEN_SEED0
    joined = hashlib.sha256("".join(shas).encode()).hexdigest()
    assert joined == GOLDEN_JOINED
    assert len(set(shas)) == 16, "distinct seeds collapsed"


# ---- verdict-equality fuzz: epoch-v2 vs epoch-v1 ---------------------------

#: histories are EXPECTED to differ across epochs (different engines,
#: different tie rules); the contract is verdict equality — the checker
#: pipeline reaches the same conclusion about both generators' runs
FUZZ_CELLS = [("register", []), ("register", ["kill"]),
              ("set", []), ("set", ["partition"])]


@pytest.mark.parametrize("workload,nemesis", FUZZ_CELLS,
                         ids=[f"{w}-{'+'.join(n) or 'none'}"
                              for w, n in FUZZ_CELLS])
def test_verdict_equality_across_epochs(tmp_path, workload, nemesis):
    from jepsen_etcd_tpu.compose import etcd_test
    from jepsen_etcd_tpu.runner.test_runner import run_test

    for seed in (11, 23):
        opts = {"workload": workload, "nemesis": list(nemesis),
                "nodes": ["n1", "n2", "n3"], "concurrency": 8,
                "rate": 200.0, "time_limit": 2, "seed": seed,
                "store_base": str(tmp_path), "no_telemetry": True}
        v1 = run_test(etcd_test(dict(opts)))["valid?"]
        g = generate_for_opts(dict(opts), [seed])
        test2 = etcd_test(dict(opts))
        d = tmp_path / f"v2-{workload}-{seed}"
        d.mkdir(exist_ok=True)
        v2 = test2["checker"].check(
            test2, g["histories"][0], {"store_dir": str(d)})["valid?"]
        assert v1 == v2 == True, (workload, nemesis, seed, v1, v2)  # noqa: E712


# ---- session-checker stale-read regression (ISSUE 13 satellite) ------------


def test_stale_injection_caught_by_session_checker():
    """The injected stale-read bug (reads may observe an old version)
    must flip the register workload's session-guarantee verdict on
    every seed, and the violations must name monotone-reads. Clean
    generation stays green — the checker does not false-positive on
    linearizable-by-construction histories."""
    from jepsen_etcd_tpu.workloads.register import workload as reg_wl

    wopts = {"nodes": ["n1", "n2", "n3"], "concurrency": 6}
    chk = reg_wl(wopts)["checker"]
    mk = dict(workload="register", lanes=6, ops_per_lane=60, rate=500.0)
    clean = generate(BatchConfig(**mk), range(4))
    stale = generate(BatchConfig(inject_stale_reads=True, **mk),
                     range(4))
    for h in clean["histories"]:
        assert chk.check(dict(wopts), h)["valid?"] is True
    for h in stale["histories"]:
        res = chk.check(dict(wopts), h)
        assert res["valid?"] is False
        sess = [v.get("session") for v in res["results"].values()
                if v.get("session")]
        bad = [s for s in sess if s["valid?"] is False]
        assert bad, "session checker missed the stale read"
        assert any(vi["guarantee"] == "monotone-reads"
                   for s in bad for vi in s.get("violations", []))
