"""Differential tests: fused Pallas wave kernel vs the jnp kernel and
the CPU oracle. The fused kernel claims definitive answers only; every
claim must match the reference engines (interpret mode off-TPU)."""

import random

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers import check_history
from jepsen_etcd_tpu.models import VersionedRegister
from jepsen_etcd_tpu.ops import wgl
from jepsen_etcd_tpu.ops import wgl_pallas

from test_wgl import gen_history


def run_both(h):
    p = wgl.pack_register_history(h)
    if not p.ok or not wgl_pallas.supported(p):
        return None
    fused = wgl_pallas.check_packed_pallas(p)
    ref = wgl.check_packed(p)
    return fused, ref, p


@pytest.mark.parametrize("corrupt", [False, True])
def test_differential_vs_jnp_kernel(corrupt):
    rng = random.Random(4242 if corrupt else 77)
    checked = 0
    for trial in range(60):
        h = gen_history(rng, n_procs=rng.randint(2, 5),
                        n_ops=rng.randint(8, 40), corrupt=corrupt)
        got = run_both(h)
        if got is None:
            continue
        fused, ref, p = got
        if fused["valid?"] == "unknown" or ref["valid?"] == "unknown":
            continue
        checked += 1
        assert fused["valid?"] == ref["valid?"], (
            f"trial {trial}: fused={fused} ref={ref['valid?']}\n"
            + h.to_jsonl())
        # same number of waves to a verdict on valid histories
        if ref["valid?"] is True:
            assert fused["waves"] == ref.get("waves"), (fused, ref)
    assert checked >= 40, f"only {checked}/60 comparable"


def test_differential_vs_cpu_oracle():
    rng = random.Random(9)
    for trial in range(30):
        h = gen_history(rng, n_procs=3, n_ops=24,
                        corrupt=(trial % 2 == 1))
        got = run_both(h)
        if got is None:
            continue
        fused, _, _ = got
        if fused["valid?"] == "unknown":
            continue
        cpu = check_history(VersionedRegister(), h, use_native=False)
        assert fused["valid?"] == cpu["valid?"], (
            f"trial {trial}: fused={fused} cpu={cpu['valid?']}\n"
            + h.to_jsonl())


def test_known_good_and_bad_fixtures():
    good = History([
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[1, 1]),
    ])
    p = wgl.pack_register_history(good)
    out = wgl_pallas.check_packed_pallas(p)
    assert out["valid?"] is True and out["engine"] == "pallas-fused"
    assert out["waves"] == p.R

    bad = History([
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[1, 2]),  # never written
    ])
    p = wgl.pack_register_history(bad)
    out = wgl_pallas.check_packed_pallas(p)
    assert out["valid?"] is False


def test_unsupported_shapes_return_none():
    # info ops break the depth==wave invariant
    h = History([
        Op(type="invoke", process=0, f="write", value=[None, 7]),
        Op(type="info", process=0, f="write", value=[None, 7],
           error="timeout"),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[1, 7]),
    ])
    p = wgl.pack_register_history(h)
    assert p.ok and p.I == 1
    assert wgl_pallas.check_packed_pallas(p) is None
