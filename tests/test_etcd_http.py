"""The real-etcd HTTP adapter, driven hermetically.

client/etcd_http.py speaks the etcd v3 gRPC-JSON gateway wire format;
sut/http_gateway.py serves that format from the simulated MVCC store.
Round-tripping the adapter against the gateway exercises the exact
bytes a live etcd would see (base64 keys/values, compare targets, txn
branches, chunked watch streams) — SURVEY §7 step 11 without needing
an etcd binary. The WallLoop (runner/wall.py) supplies real-time
scheduling under the same API the virtual-time harness uses.
"""

import threading

import pytest

from jepsen_etcd_tpu.runner.wall import WallLoop
from jepsen_etcd_tpu.runner.sim import set_current_loop, SECOND
from jepsen_etcd_tpu.client.etcd_http import HttpEtcdClient
from jepsen_etcd_tpu.client import txn as t
from jepsen_etcd_tpu.sut.http_gateway import serve
from jepsen_etcd_tpu.sut.errors import SimError


@pytest.fixture()
def gateway():
    srv, state = serve()
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    endpoint = f"http://127.0.0.1:{srv.server_address[1]}"
    yield endpoint, state
    srv.shutdown()
    srv.server_close()


def run(coro):
    loop = WallLoop()
    set_current_loop(loop)
    try:
        return loop.run_coro(coro)
    finally:
        set_current_loop(None)
        loop.shutdown()


def test_kv_roundtrip(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        assert await c.get("k") is None
        r = await c.put("k", {"a": [1, 2]})
        assert r["prev-kv"] is None
        kv = await c.get("k")
        assert kv["value"] == {"a": [1, 2]}
        assert kv["version"] == 1
        r = await c.put("k", "v2")
        assert r["prev-kv"]["value"] == {"a": [1, 2]}
        kv = await c.get("k")
        assert kv["version"] == 2
        assert await c.revision() >= kv["mod-revision"]
        return True

    assert run(main())


def test_cas_and_txn_guards(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        await c.put("reg", 1)
        ok = await c.cas("reg", 1, 2)
        assert ok["succeeded"]
        bad = await c.cas("reg", 1, 3)
        assert not bad["succeeded"]
        kv = await c.get("reg")
        assert kv["value"] == 2 and kv["version"] == 2
        # version + mod-revision guards (the append workload's shapes)
        res = await c.txn([t.eq("reg", t.version(2))],
                          [t.get("reg"), t.put("reg", 5)],
                          [t.get("reg")])
        assert res["succeeded"]
        assert res["gets"][0]["value"] == 2
        res = await c.txn(
            [t.lt("reg", t.mod_revision(1))],
            [t.put("reg", 9)], [t.get("reg")])
        assert not res["succeeded"]
        assert res["gets"][0]["value"] == 5
        return True

    assert run(main())


def test_swap_retry_loop(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        for i in range(5):
            got = await c.swap("s", lambda v: (v or 0) + 1)
            assert got == i + 1
        return True

    assert run(main())


def test_lease_lock_cycle(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        lease = await c.lease_grant(2 * SECOND)
        assert await c.lease_keepalive_once(lease) > 0
        key = await c.acquire_lock("lk", lease)
        assert key.startswith("lk/")
        await c.release_lock(key)
        await c.lease_revoke(lease)
        with pytest.raises(SimError) as ei:
            await c.lease_keepalive_once(lease)
        assert ei.value.type == "lease-not-found"
        return True

    assert run(main())


def test_lease_revoke_deletes_attached_keys(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        lease = await c.lease_grant(2 * SECOND)
        key = await c.acquire_lock("held", lease)
        assert await c.get(key) is not None
        await c.lease_revoke(lease)
        assert await c.get(key) is None  # lock key went with the lease
        return True

    assert run(main())


def test_watch_stream(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        from jepsen_etcd_tpu.runner.sim import current_loop, sleep
        loop = current_loop()
        seen = []
        done = loop.future()

        def on_events(evs):
            seen.extend(evs)
            if len(seen) >= 3:
                done.set_result(True)

        def on_error(e):
            if not done.done:
                done.set_exception(e)

        w = c.watch("w", 1, on_events, on_error)
        await sleep(int(0.1 * SECOND))
        for i in range(3):
            await c.put("w", i)
        await done
        w.cancel()
        assert [e.kv["value"] for e in seen[:3]] == [0, 1, 2]
        revs = [e.revision for e in seen]
        assert revs == sorted(revs)
        return True

    assert run(main())


def test_status_members_maintenance(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        st = await c.status()
        assert st["leader"] and "sim-gateway" in st["version"]
        ms = await c.member_list()
        assert len(ms) == 1 and ms[0]["id"] == 1
        assert await c.member_id_of_node("gw0") == 1
        await c.put("x", 1)
        await c.put("x", 2)
        await c.compact(await c.revision())
        await c.defrag()
        assert await c.await_node_ready()
        return True

    assert run(main())


def test_error_classification(gateway):
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        await c.put("e", 1)
        await c.compact(await c.revision())
        with pytest.raises(SimError) as ei:
            await c.compact(1)   # below the compact horizon
        assert ei.value.type == "compacted" and ei.value.definite
        return True

    assert run(main())


def test_connect_failure_is_indefinite():
    async def main():
        c = HttpEtcdClient("http://127.0.0.1:1")  # nothing listens
        with pytest.raises(SimError) as ei:
            await c.get("k")
        assert ei.value.type == "connect-failed"
        assert not ei.value.definite
        return True

    assert run(main())


def test_register_workload_ops_against_gateway(gateway):
    """The register client's exact op shapes (read / write-with-prev-kv
    / value-cas) round-trip the wire and produce a linearizable
    history per the checker."""
    endpoint, _ = gateway
    from jepsen_etcd_tpu.core.op import Op
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.checkers import check_history
    from jepsen_etcd_tpu.models import VersionedRegister

    async def main():
        c = HttpEtcdClient(endpoint)
        ops = []

        def rec(i, f, v):
            ops.append(Op(type="invoke", process=0, f=f,
                          value=[None, None if f == "read" else v]))
            ops.append(Op(type="ok", process=0, f=f, value=i))

        r = await c.put("r0", 3)
        prev = r.get("prev-kv")
        rec([(prev["version"] if prev else 0) + 1, 3], "write", 3)
        kv = await c.get("r0")
        rec([kv["version"], kv["value"]], "read", None)
        res = await c.cas("r0", 3, 4)
        assert res["succeeded"]
        ver = res["puts"][0]["prev-kv"]["version"] + 1
        rec([ver, [3, 4]], "cas", [3, 4])
        kv = await c.get("r0")
        rec([kv["version"], kv["value"]], "read", None)
        return History(ops)

    h = run(main())
    out = check_history(VersionedRegister(), h)
    assert out["valid?"] is True, out


# ---- round-3 advisor-fix coverage -----------------------------------------

def test_gateway_range_end_and_limit(gateway):
    """/v3/kv/range honors range_end (half-open interval) and limit —
    etcdctl get --prefix semantics (ADVICE r2)."""
    endpoint, _ = gateway
    import json as _json
    import urllib.request

    def post(path, body):
        req = urllib.request.Request(
            endpoint + path, data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as r:
            return _json.loads(r.read().decode())

    async def main():
        c = HttpEtcdClient(endpoint)
        for i in range(5):
            await c.put(f"pfx/{i}", i)
        await c.put("zzz", 99)
        return True

    assert run(main())
    from jepsen_etcd_tpu.client.etcd_http import _key64, _unkey
    # prefix scan: [pfx/, pfx0) — the etcd prefix convention
    res = post("/v3/kv/range", {"key": _key64("pfx/"),
                                "range_end": _key64("pfx0")})
    keys = [_unkey(kv["key"]) for kv in res["kvs"]]
    assert keys == [f"pfx/{i}" for i in range(5)]
    assert res["count"] == "5" and res["more"] is False
    # limit + more flag
    res = post("/v3/kv/range", {"key": _key64("pfx/"),
                                "range_end": _key64("pfx0"),
                                "limit": 2})
    assert len(res["kvs"]) == 2 and res["more"] is True
    assert res["count"] == "5"
    # from-key-onward: range_end = "\0"
    res = post("/v3/kv/range", {"key": _key64("pfx/3"),
                                "range_end": _key64("\x00")})
    keys = [_unkey(kv["key"]) for kv in res["kvs"]]
    assert keys == ["pfx/3", "pfx/4", "zzz"]
    # single-key shape unchanged
    res = post("/v3/kv/range", {"key": _key64("zzz")})
    assert len(res["kvs"]) == 1 and res["count"] == "1"


def test_lease_grant_rounds_ttl_up(gateway):
    """A 2.9s lease must become TTL=3, not 2 (ADVICE r2: truncation
    expired leases earlier than the harness's lease math assumes)."""
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        lease = await c.lease_grant(int(2.9 * SECOND))
        return await c.lease_keepalive_once(lease)

    assert run(main()) == 3 * SECOND


def test_wall_loop_waits_for_in_flight_pool_work():
    """run() must not exit idle while a run_in_thread completion is
    still in flight (ADVICE r2: its callback would be dropped)."""
    import time as _time
    loop = WallLoop()
    got = []
    fut = loop.run_in_thread(lambda: (_time.sleep(0.3), 42)[1])
    fut.add_done_callback(lambda f: got.append(f.result()))
    loop.run()  # no timers: an early idle exit would drop the callback
    assert got == [42]
    loop.shutdown()


def test_watch_compaction_cancel_carries_compact_revision(gateway):
    """A watch below the compact horizon must come back as a compacted
    cancel CARRYING the server's compact_revision (real etcd's canceled
    WatchResponse framing) — the final-watch restart uses it to resume
    at the true horizon instead of guessing from max-observed revision
    (r3 advisor finding)."""
    endpoint, _ = gateway

    async def main():
        c = HttpEtcdClient(endpoint)
        from jepsen_etcd_tpu.runner.sim import current_loop, sleep
        loop = current_loop()
        for i in range(6):
            await c.put("ck", i)
        await c.compact(5)
        done = loop.future()

        def on_events(evs):
            pass

        def on_error(e):
            if not done.done:
                done.set_result(e)

        w = c.watch("ck", 1, on_events, on_error)  # below the horizon
        err = await done
        w.cancel()
        assert isinstance(err, SimError) and err.type == "compacted", err
        assert getattr(err, "compact_revision", None) == 5, vars(err)
        return True

    assert run(main())
