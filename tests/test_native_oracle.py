"""Differential tests: native C++ WGL oracle vs the Python DFS.

The native engine (jepsen_etcd_tpu/native) must agree with the Python
oracle — the semantic reference — on every verdict, for every model it
claims to support (VersionedRegister, Mutex, CASRegister).
"""

import random

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers.linearizable import (check_history,
                                                   history_entries)
from jepsen_etcd_tpu.models import VersionedRegister, Mutex, CASRegister
from jepsen_etcd_tpu.native import oracle as native

from test_wgl import gen_history, gen_mutex_history


def test_native_lib_builds():
    assert native.get_lib() is not None, \
        "g++ is baked into the image; the native oracle must build"


@pytest.mark.parametrize("corrupt,info_rate",
                         [(False, 0.0), (True, 0.0),
                          (False, 0.25), (True, 0.25)])
def test_differential_register(corrupt, info_rate):
    rng = random.Random(hash(("native", corrupt, info_rate)) & 0xFFFF)
    for trial in range(120):
        h = gen_history(rng, n_procs=rng.randint(2, 5),
                        n_ops=rng.randint(8, 32), corrupt=corrupt,
                        info_rate=info_rate)
        nat = check_history(VersionedRegister(), h)
        py = check_history(VersionedRegister(), h, use_native=False)
        assert nat.get("checker-impl") == "native"
        assert nat["valid?"] == py["valid?"], (
            f"trial {trial}: native={nat} python={py['valid?']}\n"
            + h.to_jsonl())


@pytest.mark.parametrize("corrupt,info_rate",
                         [(False, 0.0), (True, 0.0), (False, 0.25)])
def test_differential_mutex(corrupt, info_rate):
    rng = random.Random(hash(("native-mutex", corrupt, info_rate)) & 0xFFFF)
    for trial in range(100):
        h = gen_mutex_history(rng, n_procs=rng.randint(2, 4),
                              n_ops=rng.randint(6, 24),
                              corrupt=corrupt, info_rate=info_rate)
        nat = check_history(Mutex(), h)
        py = check_history(Mutex(), h, use_native=False)
        assert nat.get("checker-impl") == "native"
        assert nat["valid?"] == py["valid?"], (
            f"trial {trial}: native={nat} python={py['valid?']}\n"
            + h.to_jsonl())


def test_invalid_history_diagnostics():
    # read of a value never written: invalid, with op + model error
    ops = [
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[1, 2]),
    ]
    out = check_history(VersionedRegister(), History(ops))
    assert out.get("checker-impl") == "native"
    assert out["valid?"] is False
    assert "op" in out and "error" in out
    assert "read" in out["error"] or "can't" in out["error"]


def test_cas_register_adapter():
    ops = [
        Op(type="invoke", process=0, f="write", value="a"),
        Op(type="ok", process=0, f="write", value="a"),
        Op(type="invoke", process=1, f="cas", value=["a", "b"]),
        Op(type="ok", process=1, f="cas", value=["a", "b"]),
        Op(type="invoke", process=0, f="read", value=None),
        Op(type="ok", process=0, f="read", value="b"),
    ]
    out = check_history(CASRegister(), History(ops))
    assert out.get("checker-impl") == "native"
    assert out["valid?"] is True
    # and an impossible read is invalid
    bad = ops + [
        Op(type="invoke", process=0, f="read", value=None),
        Op(type="ok", process=0, f="read", value="z"),
    ]
    out = check_history(CASRegister(), History(bad))
    assert out["valid?"] is False


def test_unsupported_model_returns_none():
    # non-initial model states have no register-language packing
    ents = history_entries(History([
        Op(type="invoke", process=0, f="read", value=[3, "x"]),
        Op(type="ok", process=0, f="read", value=[3, "x"]),
    ]))
    assert native.check_entries(VersionedRegister(3, "x"), ents) is None
    # and check_history still answers through the Python DFS
    out = check_history(VersionedRegister(3, "x"), History([
        Op(type="invoke", process=0, f="read", value=[3, "x"]),
        Op(type="ok", process=0, f="read", value=[3, "x"]),
    ]))
    assert out["valid?"] is True
    assert "checker-impl" not in out


def test_kill_switch(monkeypatch):
    monkeypatch.setenv("JEPSEN_ETCD_TPU_NO_NATIVE", "1")
    assert native.get_lib() is None
    out = check_history(VersionedRegister(), History([
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
    ]))
    assert out["valid?"] is True
    assert "checker-impl" not in out


def test_budget_exceeded_is_unknown():
    rng = random.Random(31)
    h = gen_history(rng, n_procs=6, n_ops=60, info_rate=0.4)
    out = check_history(VersionedRegister(), h, max_configs=3)
    assert out.get("checker-impl") == "native"
    assert out["valid?"] in ("unknown", True)  # tiny budget: likely unknown


@pytest.mark.parametrize("read_val,expect", [(1.0, True), ("1", False),
                                             (True, True)])
def test_value_equality_semantics(read_val, expect):
    """Value-id equality must be Python == (1 == 1.0 == True; '1' is
    not) so packed encodings agree with VersionedRegister.step — on the
    native engine, the Python DFS, AND the TPU kernel."""
    from jepsen_etcd_tpu.checkers.tpu_linearizable import (
        TPULinearizableChecker)
    ops = [
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=[1, 1]),
        Op(type="invoke", process=1, f="read", value=[None, None]),
        Op(type="ok", process=1, f="read", value=[1, read_val]),
    ]
    h = History(ops)
    nat = check_history(VersionedRegister(), h)
    py = check_history(VersionedRegister(), h, use_native=False)
    tpu = TPULinearizableChecker(fallback=True).check({}, h)
    assert py["valid?"] is expect
    assert nat["valid?"] is expect
    assert tpu["valid?"] is expect


def test_nonint_version_assertion_falls_back_soundly():
    """A malformed (string) version assertion must not crash and must
    match the Python DFS verdict (invalid: 'x' != any int version)."""
    ops = [
        Op(type="invoke", process=0, f="write", value=[None, 1]),
        Op(type="ok", process=0, f="write", value=["x", 1]),
    ]
    h = History(ops)
    out = check_history(VersionedRegister(), h)
    py = check_history(VersionedRegister(), h, use_native=False)
    assert out["valid?"] is py["valid?"] is False
    # and the kernel packer refuses rather than mis-encoding
    from jepsen_etcd_tpu.ops import wgl
    p = wgl.pack_register_history(h)
    assert not p.ok and "unsupported value" in p.reason


def test_native_much_faster_on_deep_history():
    """The point of the native engine: beat the Python DFS on the
    heavy fallback regime. Sanity-check a speedup on a mid-size
    history (not a benchmark, just an ordering assertion)."""
    import time
    rng = random.Random(17)
    h = gen_history(rng, n_procs=8, n_ops=160, info_rate=0.1)
    native.get_lib()  # build outside the timer
    t0 = time.time()
    nat = check_history(VersionedRegister(), h)
    t_nat = time.time() - t0
    t0 = time.time()
    py = check_history(VersionedRegister(), h, use_native=False)
    t_py = time.time() - t0
    assert nat["valid?"] == py["valid?"]
    assert t_nat < t_py, f"native {t_nat:.3f}s vs python {t_py:.3f}s"
