"""Differential tests for the batched SoA history packer.

``pack_register_histories_batched`` (ops/wgl.py) replaces the per-key
Python packing loop with one numpy pass over all K subhistories; it must
be BIT-IDENTICAL to the per-key reference (``_pack_reference``) on every
Packed field — including the lazily built frames — across info ops,
crashes, and empty keys. ``pack_perop_batch`` (ops/wgl_mxu.py) does the
same at the launch-chunk level and must match a per-key ``pack_perop``
loop exactly. On top of the packers, all four engines (CPU oracle,
native DFS, jnp ladder, MXU wave) must agree on verdicts over random
histories, both polarities.
"""

import dataclasses
import random

import numpy as np
import pytest

from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers import check_history
from jepsen_etcd_tpu.models import VersionedRegister
from jepsen_etcd_tpu.ops import wgl
from jepsen_etcd_tpu.ops import wgl_mxu

from test_wgl import gen_history


def assert_packs_equal(a, b, key=None):
    if a.ok and b.ok:
        wgl.ensure_frames(a)
        wgl.ensure_frames(b)
    for fld in dataclasses.fields(type(a)):
        x, y = getattr(a, fld.name), getattr(b, fld.name)
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            assert np.array_equal(x, y), (key, fld.name)
        else:
            assert x == y, (key, fld.name, x, y)


def gen_multi_key(rng, n_keys, info_rate=0.0, corrupt=False):
    subs = {}
    for k in range(n_keys):
        subs[k] = History(gen_history(
            rng, n_procs=rng.randint(2, 5), n_ops=rng.randint(6, 40),
            info_rate=info_rate, corrupt=corrupt))
    return subs


@pytest.mark.parametrize("info_rate", [0.0, 0.05, 0.25])
def test_batched_packer_bit_identical(info_rate):
    rng = random.Random(int(info_rate * 100) + 5)
    subs = gen_multi_key(rng, 24, info_rate=info_rate)
    batched = wgl.pack_register_histories_batched(subs)
    assert set(batched) == set(subs)
    for k, h in subs.items():
        assert_packs_equal(batched[k], wgl._pack_reference(h), key=k)


def test_batched_packer_edge_keys():
    """Empty keys, invoke-only keys, and single-op keys ride the same
    batch as normal keys without perturbing them."""
    rng = random.Random(31)
    subs = gen_multi_key(rng, 6, info_rate=0.1)
    subs["empty"] = History([])
    subs["invoke-only"] = History([{"type": "invoke", "process": 0,
                                    "f": "write", "value": [None, 1]}])
    subs["one-read"] = History([
        {"type": "invoke", "process": 0, "f": "read",
         "value": [None, None]},
        {"type": "ok", "process": 0, "f": "read", "value": [None, None]},
    ])
    batched = wgl.pack_register_histories_batched(subs)
    for k, h in subs.items():
        assert_packs_equal(batched[k], wgl._pack_reference(h), key=k)


def test_batched_packer_corrupt_histories():
    """Corrupted observations change tables, not packability — the
    batched packer must reproduce them exactly (verdict equivalence
    downstream depends on it)."""
    rng = random.Random(77)
    subs = gen_multi_key(rng, 16, corrupt=True)
    batched = wgl.pack_register_histories_batched(subs)
    for k, h in subs.items():
        assert_packs_equal(batched[k], wgl._pack_reference(h), key=k)


def test_pack_perop_batch_bit_identical():
    """Chunk-level per-op packing == per-key pack_perop loop, with
    all-zero padding keys beyond the chunk."""
    rng = random.Random(13)
    packs = []
    for _ in range(40):
        h = History(gen_history(rng, n_procs=rng.randint(2, 4),
                                n_ops=rng.randint(6, 40)))
        p = wgl.pack_register_history(h)
        if p.ok and wgl_mxu.supported(p):
            packs.append(p)
    assert len(packs) >= 20, f"only {len(packs)} supported packs"
    groups = {}
    for p in packs:
        r_pad = max(wgl_mxu.bucket(p.R), wgl_mxu.TSUB)
        groups.setdefault((r_pad, p.w), []).append(p)
    for (r_pad, _), chunk in groups.items():
        k_pad = len(chunk) + 2   # exercise padding keys
        bi, bu = wgl_mxu.pack_perop_batch(chunk, r_pad, k_pad)
        assert bi.shape == (k_pad, r_pad, 4)
        assert bu.shape == (k_pad, r_pad, 12)
        for j, p in enumerate(chunk):
            a, b = wgl_mxu.pack_perop(p, r_pad)
            assert np.array_equal(bi[j], a), j
            assert np.array_equal(bu[j], b), j
        assert not bi[len(chunk):].any()
        assert not bu[len(chunk):].any()


def test_pack_perop_batch_empty_and_zero_r():
    bi, bu = wgl_mxu.pack_perop_batch([], 128, 4)
    assert bi.shape == (4, 128, 4) and not bi.any() and not bu.any()


def test_four_engine_verdict_fuzz():
    """CPU oracle, native DFS, jnp ladder, MXU wave: identical verdicts
    wherever each claims a definitive answer, on histories packed by
    the batched packer."""
    rng = random.Random(2026)
    compared = mxu_compared = 0
    for trial in range(24):
        h = History(gen_history(rng, n_procs=rng.randint(2, 4),
                                n_ops=rng.randint(8, 32),
                                corrupt=(trial % 3 == 0)))
        cpu = check_history(VersionedRegister(), h, use_native=False)
        nat = check_history(VersionedRegister(), h)
        assert nat["valid?"] == cpu["valid?"], h.to_jsonl()
        p = wgl.pack_register_history(h)
        if not p.ok:
            continue
        lad = wgl.check_packed(p)
        if lad["valid?"] != "unknown":
            compared += 1
            assert lad["valid?"] == cpu["valid?"], h.to_jsonl()
        if wgl_mxu.supported(p):
            mxu = wgl_mxu.check_packed_mxu(p)
            if mxu["valid?"] != "unknown":
                mxu_compared += 1
                assert mxu["valid?"] == cpu["valid?"], h.to_jsonl()
    assert compared >= 12 and mxu_compared >= 8, (compared, mxu_compared)
