import pytest

from jepsen_etcd_tpu.runner.sim import (
    SimLoop, Event, Queue, Cancelled, SECOND,
    set_current_loop, sleep, wait_for, gather,
)


@pytest.fixture
def loop():
    l = SimLoop(seed=42)
    set_current_loop(l)
    yield l
    set_current_loop(None)


def test_virtual_time_sleep(loop):
    trace = []

    async def worker(name, dt):
        await sleep(dt)
        trace.append((name, loop.now))

    async def main():
        a = loop.spawn(worker("a", 3 * SECOND))
        b = loop.spawn(worker("b", 1 * SECOND))
        await gather(a, b)

    loop.run_coro(main())
    assert trace == [("b", 1 * SECOND), ("a", 3 * SECOND)]
    assert loop.now == 3 * SECOND


def test_determinism():
    def run_once():
        l = SimLoop(seed=7)
        set_current_loop(l)
        trace = []

        async def w(i):
            await sleep(l.rng.randint(0, SECOND))
            trace.append((i, l.now))

        async def main():
            await gather(*[l.spawn(w(i)) for i in range(10)])

        l.run_coro(main())
        set_current_loop(None)
        return trace

    assert run_once() == run_once()


def test_wait_for_timeout(loop):
    cancelled = []

    async def slow():
        try:
            await sleep(10 * SECOND)
        except Cancelled:
            cancelled.append(loop.now)
            raise

    async def main():
        t = loop.spawn(slow())
        with pytest.raises(TimeoutError):
            await wait_for(t, 2 * SECOND)

    loop.run_coro(main())
    assert cancelled == [2 * SECOND]
    assert loop.now == 2 * SECOND  # virtual clock did not run to 10s


def test_wait_for_success(loop):
    async def quick():
        await sleep(SECOND)
        return "done"

    async def main():
        return await wait_for(loop.spawn(quick()), 5 * SECOND)

    assert loop.run_coro(main()) == "done"


def test_event(loop):
    order = []

    async def waiter(i):
        ev_wait = ev.wait()
        await ev_wait
        order.append(i)

    async def setter():
        await sleep(SECOND)
        ev.set()

    async def main():
        ts = [loop.spawn(waiter(i)) for i in range(3)]
        loop.spawn(setter())
        await gather(*ts)

    ev = None

    async def top():
        nonlocal ev
        ev = Event(loop)
        await main()

    loop.run_coro(top())
    assert order == [0, 1, 2]


def test_queue(loop):
    got = []

    async def consumer(q):
        for _ in range(3):
            got.append(await q.get())

    async def main():
        q = Queue(loop)
        c = loop.spawn(consumer(q))
        q.put(1)
        await sleep(SECOND)
        q.put(2)
        q.put(3)
        await c

    loop.run_coro(main())
    assert got == [1, 2, 3]


def test_exception_propagates(loop):
    async def boom():
        await sleep(1)
        raise ValueError("boom")

    async def main():
        await loop.spawn(boom())

    with pytest.raises(ValueError):
        loop.run_coro(main())


def test_max_time_resumable(loop):
    # Regression: run(max_time=) must not drop the event it stops before.
    ticks = []

    async def ticker():
        for _ in range(4):
            await sleep(2 * SECOND)
            ticks.append(loop.now)

    t = loop.spawn(ticker())
    loop.run(max_time=3 * SECOND)
    assert ticks == [2 * SECOND]
    loop.run(until=t)  # resume: the 4s wakeup must still fire
    assert ticks == [2 * SECOND, 4 * SECOND, 6 * SECOND, 8 * SECOND]


def test_wait_for_success_leaves_clock_clean(loop):
    # Regression: stale timeout timers must not inflate the clock on drain.
    async def quick():
        await sleep(SECOND)
        return 1

    async def main():
        return await wait_for(loop.spawn(quick()), 3600 * SECOND)

    loop.run_coro(main())
    loop.run()  # full drain
    assert loop.now == SECOND


def test_queue_get_cancelled_does_not_lose_items(loop):
    # Regression: an item delivered to a cancelled getter must be re-queued.
    got = []

    async def getter(q):
        return await q.get()

    async def main():
        q = Queue(loop)
        t1 = loop.spawn(getter(q))
        await sleep(1)
        t1.cancel()
        q.put("x")  # may race with the cancellation delivery
        await sleep(1)
        t2 = loop.spawn(getter(q))
        got.append(await t2)

    loop.run_coro(main())
    assert got == ["x"]


def test_gather_cancel_propagates(loop):
    # Regression: cancelling a task blocked in gather() must terminate it.
    async def hang():
        await loop.future()

    async def gatherer():
        await gather(loop.spawn(hang()), loop.spawn(hang()))

    async def main():
        t = loop.spawn(gatherer())
        await sleep(1)
        t.cancel()
        await sleep(1)
        assert t.done

    loop.run_coro(main())


def test_queue_reroute_wakes_other_getter(loop):
    # Regression: item delivered to a cancelled getter goes to the next
    # waiting getter, not stranded in the buffer.
    got = []

    async def getter(q):
        got.append(await q.get())

    async def main():
        q = Queue(loop)
        t1 = loop.spawn(getter(q))
        t2 = loop.spawn(getter(q))
        await sleep(1)
        t1.cancel()
        q.put("x")
        await sleep(1)
        assert got == ["x"]
        assert len(q) == 0

    loop.run_coro(main())


def test_gather_child_cancel_does_not_kill_gatherer(loop):
    # Regression: a cancelled child is a child failure, not our cancellation.
    async def hang():
        await loop.future()

    async def quick():
        await sleep(1)
        return "ok"

    async def main():
        t1 = loop.spawn(hang())
        t2 = loop.spawn(quick())
        g = loop.spawn(gather(t1, t2))
        await sleep(2)
        t1.cancel()
        with pytest.raises(Cancelled):
            await g
        assert t2.done and t2.result() == "ok"

    loop.run_coro(main())


def test_cancelled_timer_tombstones_compact(loop):
    # Regression (r6): cancelling timers leaves tombstones in the heap;
    # once they dominate (and exceed COMPACT_FLOOR) the loop compacts
    # instead of letting the heap grow without bound.
    n = 4 * SimLoop.COMPACT_FLOOR
    timers = [loop.call_later(i + 1, lambda: None) for i in range(n)]
    assert len(loop._heap) == n
    # cancel all but a few: tombstones dominate -> compaction fires
    for t in timers[:-4]:
        t.cancel()
    assert all(t.cancelled for t in timers[:-4])
    # compaction fired (repeatedly): the heap stays bounded by the
    # floor instead of holding all n-4 tombstones, and every non-live
    # entry still in it is accounted for in _dead
    assert len(loop._heap) - 4 == loop._dead
    assert loop._dead <= 2 * SimLoop.COMPACT_FLOOR
    assert len(loop._heap) < n // 2
    # heap invariant survived compaction: survivors still fire in order
    fired = []
    for j, t in enumerate(timers[-4:]):
        t._entry[2] = lambda j=j: fired.append(j)
    loop.run()
    assert fired == [0, 1, 2, 3]


def test_cancel_below_floor_keeps_tombstones(loop):
    # Below COMPACT_FLOOR a filter+heapify costs more than popping the
    # dead entries during run(); cancel() must leave them in place.
    timers = [loop.call_later(i + 1, lambda: None) for i in range(8)]
    for t in timers[:6]:
        t.cancel()
    assert len(loop._heap) == 8 and loop._dead == 6
    loop.run()                  # drains tombstones without firing them
    assert loop._dead == 0 and not loop._heap


def test_same_instant_batch_drains_in_seq_order(loop):
    # The batched same-instant drain must preserve (time, seq) order,
    # including entries a callback pushes at the SAME instant.
    fired = []
    loop.call_at(5, lambda: fired.append("a"))
    loop.call_at(5, lambda: (fired.append("b"),
                             loop.call_at(5, lambda: fired.append("d"))))
    loop.call_at(5, lambda: fired.append("c"))
    loop.run()
    assert fired == ["a", "b", "c", "d"]
    assert loop.now == 5
