"""Campaign driver e2e (runner/campaign.py): pooled fan-out, per-run
stores, the exit-code contract, and — the headline — cross-run dispatch
amortization through the shared checker service, with every service
verdict bit-identical to an in-process re-check of the same stored
history.
"""

import json
import os
import threading

from jepsen_etcd_tpu.forensics import load_history
from jepsen_etcd_tpu.runner.campaign import (campaign_specs,
                                             run_campaign)
from jepsen_etcd_tpu.runner.store import make_store_dir

#: verdict projection compared between the service-checked run and the
#: in-process re-check (metadata like "rungs"/"batched" legitimately
#: varies with group composition; tests/test_checker_service.py pins
#: the same projection at the wgl layer)
PROJECTION = ("valid?", "waves", "peak-frontier", "ops", "info-ops",
              "op", "error", "stuck-at-depth")


def test_campaign_specs_expand_with_distinct_seeds():
    specs = campaign_specs({"rate": 5.0}, ["register", "set"],
                           [[], ["kill"]], runs_per_cell=3, seed0=10)
    assert len(specs) == 12
    assert [s["index"] for s in specs] == list(range(12))
    seeds = [s["opts"]["seed"] for s in specs]
    assert seeds == list(range(10, 22))
    assert {s["opts"]["workload"] for s in specs} == {"register", "set"}


def test_store_dirs_are_collision_safe(tmp_path):
    """Concurrent make_store_dir calls (the pooled campaign's worker
    processes racing on one base) must never hand two callers the same
    directory."""
    base = str(tmp_path)
    dirs: list = []
    lock = threading.Lock()

    def claim():
        d = make_store_dir(base, "race")
        with lock:
            dirs.append(d)

    threads = [threading.Thread(target=claim) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(dirs) == 16
    assert len(set(dirs)) == 16, "two callers claimed one run dir"
    for d in dirs:
        assert os.path.isdir(d)


def test_campaign_pool_e2e(tmp_path):
    """12 sim runs over a pool of 3 spawned workers: every run gets
    its own store dir with saved artifacts, rows come back indexed,
    and the aggregate verdict follows the test-all exit-code
    contract."""
    # rate high enough that every seed lands >=1 ok op per f (else the
    # stats checker honestly says "unknown", which fails the
    # expected-pass contract); sim runs are seed-deterministic, so
    # these exact opts were verified all-True once and stay that way
    base = {"time_limit": 1, "rate": 100.0,
            "nodes": ["n1", "n2", "n3"]}
    specs = campaign_specs(base, ["register"], [[], ["kill"]],
                           runs_per_cell=6, seed0=7)
    assert len(specs) == 12
    summary = run_campaign(specs, pool=3, service=False,
                           store_base=str(tmp_path), name="e2e")
    assert summary["valid?"] is True
    assert summary["failures"] == []
    rows = summary["runs"]
    assert [r["index"] for r in rows] == list(range(12))
    assert all(r["status"] == "done" and r["valid"] is True
               for r in rows)
    dirs = {r["dir"] for r in rows}
    assert len(dirs) == 12, "runs shared a store dir"
    for r in rows:
        assert os.path.isfile(os.path.join(r["dir"], "results.json"))
        assert os.path.isfile(os.path.join(r["dir"], "history.jsonl"))
        assert r["ops"] > 0
    ctr = (summary["telemetry"].get("counters") or {})
    assert ctr.get("campaign.runs") == 12
    assert ctr.get("campaign.completed") == 12
    assert not ctr.get("campaign.failed")
    cjson = os.path.join(summary["dir"], "campaign.json")
    assert json.load(open(cjson))["count"] == 12
    # the campaign surfaces on the aggregate dashboard
    from jepsen_etcd_tpu.serve import aggregate_html
    page = aggregate_html(str(tmp_path))
    assert "Campaign perf trends" in page and "e2e/" in page


def test_campaign_counts_errors_and_fails(tmp_path):
    """A crashing run is one error row, not a dead sweep — and it
    fails the campaign."""
    ok = {"opts": {"workload": "register", "time_limit": 1,
                   "rate": 40.0, "seed": 3,
                   "nodes": ["n1", "n2", "n3"]}}
    bad = {"opts": {"workload": "no-such-workload", "time_limit": 1,
                    "rate": 40.0, "seed": 4,
                    "nodes": ["n1", "n2", "n3"]}}
    summary = run_campaign([ok, bad], pool=0, service=False,
                           store_base=str(tmp_path), name="mixed")
    rows = summary["runs"]
    assert rows[0]["status"] == "done" and rows[0]["valid"] is True
    assert rows[1]["status"] == "error"
    assert summary["valid?"] is False
    assert len(summary["failures"]) == 1
    ctr = (summary["telemetry"].get("counters") or {})
    assert ctr.get("campaign.completed") == 1
    assert ctr.get("campaign.errors") == 1


def _recheck_locally(run_dir: str) -> dict:
    """Re-run the run's own checker in-process (no service) over its
    saved history; returns {key: linear-verdict-projection}."""
    from jepsen_etcd_tpu.workloads.register import workload
    test = json.load(open(os.path.join(run_dir, "test.json")))
    test.pop("checker_service", None)
    checker = workload(test)["checker"]
    res = checker.check(test, load_history(run_dir))
    return {str(k): {f: (v.get("linear") or {}).get(f)
                     for f in PROJECTION}
            for k, v in res["results"].items()}


def test_batchable_gate():
    """Routing guard: only epoch-v2 sim runs of supported workloads go
    through the batched generator; live/stream/soak specs fall back to
    the epoch-v1 pool."""
    from jepsen_etcd_tpu.runner.campaign import _batchable

    sim = {"workload": "register", "gen_epoch": "epoch-v2"}
    assert _batchable(dict(sim))
    assert _batchable(dict(sim, workload="set"))
    assert not _batchable(dict(sim, gen_epoch="epoch-v1"))
    assert not _batchable(dict(sim, client_type="http"))
    assert not _batchable(dict(sim, db_mode="local"))
    assert not _batchable(dict(sim, stream=True))
    assert not _batchable(dict(sim, soak=True))
    assert not _batchable(dict(sim, workload="watch"))


def test_campaign_epoch_v2_batched_routing(tmp_path):
    """ISSUE 13 acceptance: with --gen-epoch epoch-v2 the campaign
    generates each (workload, nemesis) cell's seeds in ONE lockstep
    batched pass, records the generator epoch per run in campaign.json,
    and every per-run verdict is bit-identical to an in-process
    re-check of the run's stored history."""
    base = {"time_limit": 1, "rate": 100.0, "nodes": ["n1", "n2", "n3"],
            "gen_epoch": "epoch-v2"}
    specs = campaign_specs(base, ["register"], [[], ["kill"]],
                           runs_per_cell=3, seed0=50)
    summary = run_campaign(specs, pool=0, service=False,
                           store_base=str(tmp_path), name="batched")
    assert summary["valid?"] is True, summary["failures"]
    rows = summary["runs"]
    assert len(rows) == 6
    assert all(r["status"] == "done" and r["valid"] is True
               for r in rows)
    assert all(r["gen-epoch"] == "epoch-v2" for r in rows)
    gb = summary["genbatch"]
    assert gb["cells"] == 2 and gb["seeds"] == 6
    assert gb["epoch"] == "epoch-v2" and gb["ops_per_s"] > 0
    ctr = (summary["telemetry"].get("counters") or {})
    assert ctr.get("genbatch.cells") == 2
    assert ctr.get("genbatch.seeds") == 6
    # the epoch ledger lands on disk with the rows
    cjson = json.load(open(os.path.join(summary["dir"],
                                        "campaign.json")))
    assert cjson["genbatch"]["cells"] == 2
    assert [r["gen-epoch"] for r in cjson["runs"]] == ["epoch-v2"] * 6
    # verdict bit-identity vs an in-process re-check of the stored
    # history (same projection the pooled coalescing test pins)
    for r in rows:
        stored = json.load(
            open(os.path.join(r["dir"], "results.json")))
        got = {str(k): {f: (v.get("linear") or {}).get(f)
                        for f in PROJECTION}
               for k, v in stored["workload"]["results"].items()}
        assert got == _recheck_locally(r["dir"]), r["dir"]


def test_campaign_epoch_v1_rows_record_epoch(tmp_path):
    """Without the flag, pooled sim rows still carry the ledger entry:
    gen-epoch epoch-v1 (and live rows would carry None)."""
    ok = {"opts": {"workload": "register", "time_limit": 1,
                   "rate": 100.0, "seed": 7,
                   "nodes": ["n1", "n2", "n3"]}}
    summary = run_campaign([ok], pool=0, service=False,
                           store_base=str(tmp_path), name="v1")
    assert summary["runs"][0]["gen-epoch"] == "epoch-v1"
    assert summary["genbatch"] is None


def test_campaign_coalescing_50_runs(tmp_path):
    """The acceptance bar: a 50-run forced-kernel campaign through the
    shared service coalesces every device-bound check into at most one
    dispatch per (bucket, width, tick) — proven by the campaign's own
    folded counters — and every stored verdict is bit-identical to an
    in-process re-check of the same history."""
    base = {"time_limit": 1, "rate": 100.0, "force_kernel": True,
            "nodes": ["n1", "n2", "n3"]}
    specs = campaign_specs(base, ["register"], [[]],
                           runs_per_cell=50, seed0=100)
    summary = run_campaign(specs, pool=4, service=True,
                           service_tick_s=0.05,
                           store_base=str(tmp_path), name="coalesce")
    assert summary["valid?"] is True, summary["failures"]
    rows = summary["runs"]
    assert len(rows) == 50
    assert all(r["status"] == "done" and r["valid"] is True
               for r in rows)
    ctr = (summary["telemetry"].get("counters") or {})
    assert ctr.get("campaign.completed") == 50

    # -- dispatch-amortization ledger ------------------------------------
    submitted = ctr.get("service.submitted", 0)
    group_ticks = ctr.get("service.group_ticks", 0)
    dispatches = (ctr.get("wgl.dispatches", 0)
                  + ctr.get("mxu.dispatches", 0))
    assert submitted >= 50, ctr     # every run shipped >= 1 pack
    assert 0 < group_ticks < submitted, ctr   # coalescing happened
    # <= 1 device launch per (bucket, width, tick): the tentpole bar
    assert dispatches <= group_ticks, ctr
    assert ctr.get("service.batch_occupancy", 0) >= 2, ctr
    assert not ctr.get("service.fallback"), ctr
    # workers shipped ALL device work — no local dispatches, and the
    # producer-side ledger balances: packs shipped by the runs equal
    # packs the service says it received
    assert sum(r["dispatches"] for r in rows) == 0
    assert sum(r["service_fallbacks"] for r in rows) == 0
    assert sum(r["service_shipped"] for r in rows) == submitted

    # -- multi-device placement ledger (8 fake chips via conftest) -------
    # every chip works, no chip hoards (single-group ticks shard the
    # batch axis over the full mesh), and the shipped==submitted
    # identity extends per device: Σ per-device dispatches balances
    # group ticks plus the sharded fan-out exactly
    disp = {k.rsplit(".", 1)[1]: v for k, v in ctr.items()
            if k.startswith("service.device_dispatches.")}
    assert set(disp) == {f"cpu{i}" for i in range(8)}, disp
    assert max(disp.values()) <= 2 * min(disp.values()), disp
    assert sum(disp.values()) == (group_ticks
                                  + ctr.get("service.shard_fanout", 0)), ctr
    assert ctr.get("service.device_occupancy") == 8, ctr

    # -- verdict bit-identity vs in-process re-check ---------------------
    for r in rows:
        stored = json.load(
            open(os.path.join(r["dir"], "results.json")))
        got = {str(k): {f: (v.get("linear") or {}).get(f)
                        for f in PROJECTION}
               for k, v in stored["workload"]["results"].items()}
        want = _recheck_locally(r["dir"])
        assert got == want, r["dir"]
