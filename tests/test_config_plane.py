"""The SUT config plane end-to-end: CLI opts must actually reach the
cluster (etcd.clj:164,197-204 -> db.clj:88-99), and the --corrupt-check
monitor must catch silent divergence."""

import pytest

from jepsen_etcd_tpu.cli import build_parser, opts_from_args
from jepsen_etcd_tpu.cli import test_all_matrix as _test_all_matrix
from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.test_runner import run_test
from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop, SECOND
from jepsen_etcd_tpu.sut.cluster import Cluster, ClusterConfig, FP_EVERY
from jepsen_etcd_tpu.checkers import LogFilePattern
from jepsen_etcd_tpu.workloads import ALL_WORKLOADS, WORKLOADS_EXPECTED_TO_PASS


def run(tmp_path, **opts):
    base = {"time_limit": 6, "rate": 50, "ops_per_key": 30,
            "store_base": str(tmp_path), "seed": 7}
    base.update(opts)
    test = etcd_test(base)
    out = run_test(test)
    out["test"] = test
    return out


# ---- snapshot-count / unsafe-no-fsync threading ---------------------------

def test_snapshot_count_reaches_cluster_and_changes_cadence(tmp_path):
    """--snapshot-count 5 must produce snapshots in a short run where the
    default 100 produces none on most nodes (etcd.clj:197-200)."""
    out = run(tmp_path, workload="register", snapshot_count=5)
    cluster = out["test"]["cluster"]
    assert cluster.cfg.snapshot_count == 5
    snaps = [n.snap_index for n in cluster.nodes.values()]
    assert max(snaps) > 0, "no node ever snapshotted at count=5"
    saved = [line for node in cluster.nodes.values()
             for line in node.etcd_log if "saved snapshot" in line]
    assert saved


def test_unsafe_no_fsync_reaches_cluster(tmp_path):
    out = run(tmp_path, workload="register", unsafe_no_fsync=True)
    assert out["test"]["cluster"].cfg.unsafe_no_fsync is True
    # and the default matches etcd's: fsync ON unless the flag is given
    out2 = run(tmp_path, workload="register")
    assert out2["test"]["cluster"].cfg.unsafe_no_fsync is False


def test_cli_flags_reach_opts():
    args = build_parser().parse_args(
        ["test", "--snapshot-count", "7", "--unsafe-no-fsync",
         "--corrupt-check", "-v", "sim-3.5.6"])
    opts = opts_from_args(args)
    assert opts["snapshot_count"] == 7
    assert opts["unsafe_no_fsync"] is True
    assert opts["corrupt_check"] is True
    assert opts["version"] == "sim-3.5.6"
    # defaults mirror the reference CLI (etcd.clj:157-209)
    d = opts_from_args(build_parser().parse_args(["test"]))
    assert d["workload"] == "register"
    assert d["snapshot_count"] == 100
    assert d["unsafe_no_fsync"] is False
    assert d["corrupt_check"] is False
    assert d["net_proxy"] is False
    p = opts_from_args(build_parser().parse_args(["test", "--net-proxy"]))
    assert p["net_proxy"] is True


# ---- fault / privilege matrix (README table) -------------------------------

def test_fault_matrix_rows():
    """The rows the README table and `--db local` refusals are built
    from: partition + latency flipped to supported by the proxy plane
    (PR 11); clock and corruption stay refused with specific reasons."""
    from jepsen_etcd_tpu.compose import fault_matrix
    from jepsen_etcd_tpu.nemesis.faults import KNOWN_FAULTS
    local = fault_matrix("local")
    assert set(local) == set(KNOWN_FAULTS)
    assert local["partition"] == {"supported": True, "why": None}
    assert local["latency"] == {"supported": True, "why": None}
    for fault in ("kill", "pause", "member", "admin"):
        assert local[fault]["supported"] is True, fault
    assert local["clock"]["supported"] is False
    assert "CAP_SYS_TIME" in local["clock"]["why"]
    for fault in ("bitflip-wal", "bitflip-snap", "truncate-wal"):
        assert local[fault]["supported"] is False, fault
        assert "corruption" in local[fault]["why"], fault
    sim = fault_matrix("sim")
    assert all(row["supported"] for row in sim.values())
    live = fault_matrix("live")
    assert not any(row["supported"] for row in live.values())
    assert all(row["why"] for row in live.values())


# ---- corrupt-check monitor ------------------------------------------------

def _advance(cluster, loop, writes):
    """Drive enough writes through the leader for FP_EVERY-multiple
    fingerprints to be recorded on every node."""
    from jepsen_etcd_tpu.client.direct import DirectClient

    async def go():
        c = DirectClient(cluster, "n1")
        await c.await_node_ready()
        for i in range(writes):
            await c.put(f"k{i % 8}", f"v{i}")
    loop.run_coro(go())
    # let replication/apply drain
    loop.run_coro(_sleep(2 * SECOND))


async def _sleep(dt):
    from jepsen_etcd_tpu.runner.sim import sleep
    await sleep(dt)


@pytest.fixture
def corrupt_cluster():
    loop = SimLoop(seed=3)
    set_current_loop(loop)
    cluster = Cluster(loop, ["n1", "n2", "n3"],
                      ClusterConfig(corrupt_check=True))
    cluster.launch()
    yield cluster, loop
    cluster.shutdown()
    set_current_loop(None)


def test_clean_cluster_no_alarm(corrupt_cluster):
    cluster, loop = corrupt_cluster
    _advance(cluster, loop, 2 * FP_EVERY)
    assert any(n.fp_ledger for n in cluster.nodes.values()), \
        "fingerprint ledger never recorded"
    assert cluster.check_corruption() == []
    assert cluster.corruption_alarms == []


def test_bitflipped_but_replayable_node_trips_alarm(corrupt_cluster):
    """A store that silently diverges (the bitflip-that-passes-CRC case)
    must raise the corruption alarm with a fatal log line the
    crash-pattern checker catches."""
    cluster, loop = corrupt_cluster
    _advance(cluster, loop, 2 * FP_EVERY)
    victim = cluster.nodes["n2"]
    key = sorted(victim.store.kvs)[0]
    victim.store.kvs[key].value = "corrupted-bits"
    # poison the ledger too, as a silently-bad replay would
    for idx in victim.fp_ledger:
        victim.fp_ledger[idx] ^= 0xDEADBEEF
    alarms = cluster.check_corruption()
    assert alarms, "divergence not detected"
    assert any("n2" in a["nodes"] for a in alarms)
    # the fatal alarm line matches the crash-pattern regex
    check = LogFilePattern().check({"cluster": cluster}, [])
    assert check["valid?"] is False
    assert any("data inconsistency" in m["line"] for m in check["matches"])
    # re-checking does not duplicate alarms
    n = len(cluster.corruption_alarms)
    cluster.check_corruption()
    assert len(cluster.corruption_alarms) == n


def test_corrupt_check_e2e_clean_run(tmp_path):
    """--corrupt-check on a healthy run: monitor runs, verdict present
    and valid."""
    out = run(tmp_path, workload="register", corrupt_check=True,
              time_limit=8)
    assert out["test"]["cluster"].cfg.corrupt_check is True
    cc = out["results"]["corrupt-check"]
    assert cc["valid?"] is True and cc["alarms"] == []
    assert out["valid?"] is True
    assert any(n.fp_ledger for n in
               out["test"]["cluster"].nodes.values())


# ---- test-all narrowing (etcd.clj:236-242) --------------------------------

def _args(extra):
    return build_parser().parse_args(["test-all"] + extra)


def test_test_all_default_matrix():
    wls, nems = _test_all_matrix(_args([]))
    assert wls == ALL_WORKLOADS          # :none excluded (etcd.clj:48-49)
    assert "none" not in wls
    assert len(nems) == 9
    # drift guard: the sweep list must track the registry
    from jepsen_etcd_tpu.workloads import workloads
    assert set(ALL_WORKLOADS) == set(workloads()) - {"none"}


def test_test_all_workload_narrowing():
    wls, nems = _test_all_matrix(_args(["-w", "set"]))
    assert wls == ["set"] and len(nems) == 9


def test_test_all_nemesis_narrowing():
    wls, nems = _test_all_matrix(_args(["--nemesis", "kill,partition"]))
    assert nems == [["kill", "partition"]]
    assert wls == ALL_WORKLOADS


def test_expected_to_pass_matches_reference():
    """etcd.clj:51-53 removes only :lock and :lock-set from all-workloads;
    lock-etcd-set is expected to PASS."""
    assert "lock-etcd-set" in WORKLOADS_EXPECTED_TO_PASS
    assert "lock" not in WORKLOADS_EXPECTED_TO_PASS
    assert "lock-set" not in WORKLOADS_EXPECTED_TO_PASS
    assert "none" not in WORKLOADS_EXPECTED_TO_PASS
    assert set(WORKLOADS_EXPECTED_TO_PASS) == \
        set(ALL_WORKLOADS) - {"lock", "lock-set"}
