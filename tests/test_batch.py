"""The batched production checker: one vmapped kernel launch per key
batch, key axis sharded over the device mesh (VERDICT r1 item 2; SURVEY
§2.3 "vmap over keys is the main DP axis of the TPU checker";
register.clj:108-119 is the per-key decomposition being parallelized).
"""

import random

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers import compose, independent_checker
from jepsen_etcd_tpu.checkers.independent import Independent
from jepsen_etcd_tpu.checkers.tpu_linearizable import TPULinearizableChecker
from jepsen_etcd_tpu.ops import wgl

from test_wgl import gen_history


def keyed(history, key, p_base):
    """Wrap a per-key history into (key, v) tuple values with disjoint
    process ids, as independent.concurrent_generator records them."""
    out = []
    for op in history:
        out.append(op.evolve(value=(key, op.get("value")),
                             process=op.get("process") + p_base,
                             index=None))
    return out


def multi_key_history(n_keys, rng, corrupt_keys=(), info_rate=0.0):
    ops = []
    for k in range(n_keys):
        sub = gen_history(rng, n_procs=3, n_ops=18,
                          corrupt=(k in corrupt_keys), info_rate=info_rate)
        ops.extend(keyed(sub, k, 100 * k))
    return History(ops)


def test_16_keys_single_batched_launch(monkeypatch):
    """A 16-key register check issues ONE batched kernel call and zero
    per-key launches (VERDICT done-criterion)."""
    calls = {"batch": 0, "single": 0}
    real_batch = wgl.check_packed_batch
    real_single = wgl.check_packed

    def spy_batch(packs, f_max=None, **kw):
        calls["batch"] += 1
        return real_batch(packs, f_max=f_max, **kw)

    def spy_single(p, f_max=None):
        calls["single"] += 1
        return real_single(p, f_max=f_max)

    monkeypatch.setattr(wgl, "check_packed_batch", spy_batch)
    monkeypatch.setattr(wgl, "check_packed", spy_single)

    rng = random.Random(41)
    h = multi_key_history(16, rng)
    out = Independent(TPULinearizableChecker(cpu_cutoff=None)).check({}, h)
    assert out["valid?"] is True
    assert out["key-count"] == 16
    assert calls["batch"] == 1
    assert calls["single"] == 0
    for r in out["results"].values():
        assert r.get("batched") is True
        assert r["checker"] == "tpu-wgl"


def test_batch_matches_per_key_results():
    """Batched verdicts must equal per-key kernel verdicts, including an
    invalid key (with CPU counterexample diagnostics attached) among
    valid ones."""
    rng = random.Random(77)
    # find a seedful corrupt key whose per-key verdict is False
    h = multi_key_history(6, rng, corrupt_keys=(2, 4))
    checker = TPULinearizableChecker(cpu_cutoff=None)
    batched = Independent(checker).check({}, h)
    from jepsen_etcd_tpu.generators.independent import history_keys, subhistory
    for k in history_keys(h):
        sub = History(subhistory(h, k))
        single = checker.check({}, sub)
        assert batched["results"][k]["valid?"] == single["valid?"], k
        if single["valid?"] is False:
            # diagnostics attached on the batch path too
            assert "op" in batched["results"][k] or \
                "error" in batched["results"][k]
    if any(batched["results"][k]["valid?"] is False
           for k in batched["results"]):
        assert batched["valid?"] is False


def test_batch_with_info_ops():
    """Faulted (info-op) histories stay on the batched TPU path."""
    rng = random.Random(5)
    h = multi_key_history(8, rng, info_rate=0.2)
    out = Independent(TPULinearizableChecker(cpu_cutoff=None)).check({}, h)
    for k, r in out["results"].items():
        assert r["checker"] in ("tpu-wgl",), (k, r)


def test_batch_uneven_sizes_and_empty_key():
    """Keys with different lengths (different R buckets) and an
    all-info key (R=0) batch together correctly."""
    rng = random.Random(13)
    ops = []
    ops.extend(keyed(gen_history(rng, n_procs=2, n_ops=6), "small", 0))
    ops.extend(keyed(gen_history(rng, n_procs=4, n_ops=40), "big", 100))
    # R=0 key: a single info op, no required ops
    ops.append(Op(type="invoke", process=500, f="write",
                  value=("empty", [None, 3])))
    ops.append(Op(type="info", process=500, f="write",
                  value=("empty", [None, 3]), error="timeout"))
    out = Independent(TPULinearizableChecker(cpu_cutoff=None)).check({}, History(ops))
    assert out["valid?"] is True
    assert set(out["results"]) == {"small", "big", "empty"}
    assert out["results"]["empty"]["valid?"] is True


def test_compose_forwards_batch(monkeypatch):
    """The production wiring — Independent(compose({linear: TPU, ...}))
    — reaches the batched kernel exactly once."""
    calls = {"batch": 0}
    real_batch = wgl.check_packed_batch

    def spy(packs, f_max=None, **kw):
        calls["batch"] += 1
        return real_batch(packs, f_max=f_max, **kw)

    monkeypatch.setattr(wgl, "check_packed_batch", spy)
    rng = random.Random(3)
    h = multi_key_history(4, rng)
    from jepsen_etcd_tpu.checkers import Stats
    out = independent_checker(compose({
        "linear": TPULinearizableChecker(cpu_cutoff=None),
        "stats": Stats(),
    })).check({}, h)
    assert out["valid?"] is True
    assert calls["batch"] == 1
    for r in out["results"].values():
        assert r["linear"]["checker"] == "tpu-wgl"
        assert "count" in r["stats"]
