import pytest

from jepsen_etcd_tpu.core.op import Op, NEMESIS
from jepsen_etcd_tpu.generators import (
    mix, limit, stagger, time_limit, phases, reserve, nemesis, clients,
    each_thread, sleep_gen, log, independent, repeat,
)
from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop, sleep, SECOND
from jepsen_etcd_tpu.runner.interpreter import interpret


def run_gen(gen, concurrency=4, seed=0, latency=int(0.05 * SECOND),
            invoke=None, nemesis_invoke=None, test=None):
    loop = SimLoop(seed=seed)
    set_current_loop(loop)

    async def default_invoke(process, op):
        await sleep(loop.rng.randint(1, latency))
        return op.evolve(type="ok")

    async def main():
        return await interpret(test or {}, gen, invoke or default_invoke,
                               concurrency, nemesis_invoke=nemesis_invoke)

    h = loop.run_coro(main())
    set_current_loop(None)
    return h


def r(test, ctx):
    return {"f": "read", "value": None}


def w(test, ctx):
    return {"f": "write", "value": ctx.rng.randint(0, 4)}


def test_limit_and_mix():
    h = run_gen(limit(20, mix([r, w])))
    invokes = h.invokes()
    assert len(invokes) == 20
    fs = {op.f for op in invokes}
    assert fs == {"read", "write"}
    # every op completes
    assert all(h.completion(op) is not None for op in invokes)


def test_reserve_partitions_threads():
    # 2 threads read-only, remaining 2 write-only (set.clj:47 shape)
    gen = limit(40, reserve(2, repeat({"f": "read"}), repeat({"f": "write"})))
    h = run_gen(gen, concurrency=4)
    for op in h.invokes():
        thread = op.process % 4
        if op.f == "read":
            assert thread in (0, 1)
        else:
            assert thread in (2, 3)


def test_stagger_rate():
    # 50 ops at mean 0.1s spacing ~ 5s total
    gen = limit(50, stagger(int(0.1 * SECOND), r))
    h = run_gen(gen, concurrency=4)
    times = [op.time for op in h.invokes()]
    total = (times[-1] - times[0]) / SECOND
    assert 2.0 < total < 10.0  # mean gap 0.1s -> ~4.9s expected


def test_time_limit_cuts_off():
    gen = time_limit(1 * SECOND, stagger(int(0.01 * SECOND), r))
    h = run_gen(gen, concurrency=4)
    assert len(h) > 10
    assert all(op.time <= 1 * SECOND for op in h.invokes())


def test_phases_barrier():
    gen = phases(
        limit(8, repeat({"f": "a"})),
        limit(8, repeat({"f": "b"})),
    )
    h = run_gen(gen, concurrency=4)
    assert len([op for op in h.invokes() if op.f == "a"]) == 8
    assert len([op for op in h.invokes() if op.f == "b"]) == 8
    a_completes = [op.time for op in h if op.is_completion and op.f == "a"]
    b_invokes = [op.time for op in h if op.is_invoke and op.f == "b"]
    assert a_completes and b_invokes
    assert min(b_invokes) >= max(a_completes)


def test_each_thread():
    h = run_gen(each_thread({"f": "final"}), concurrency=4)
    invs = h.invokes()
    assert len(invs) == 4
    assert {op.process % 4 for op in invs} == {0, 1, 2, 3}


def test_nemesis_routing():
    async def nem_invoke(op):
        await sleep(int(0.02 * SECOND))
        return op.evolve(type="info")

    gen = time_limit(
        2 * SECOND,
        nemesis(
            repeat({"f": "kill"}),
            stagger(int(0.05 * SECOND), r),
        ),
    )
    h = run_gen(gen, concurrency=2, nemesis_invoke=nem_invoke)
    kills = [op for op in h if op.f == "kill"]
    reads = [op for op in h if op.f == "read"]
    assert kills and reads
    assert all(op.process == NEMESIS for op in kills)
    assert all(isinstance(op.process, int) for op in reads)


def test_info_bumps_process():
    count = [0]

    async def flaky(process, op):
        await sleep(int(0.01 * SECOND))
        count[0] += 1
        if count[0] == 3:
            return op.evolve(type="info", error="timeout")
        return op.evolve(type="ok")

    h = run_gen(limit(10, r), concurrency=2, invoke=flaky)
    procs = {op.process for op in h.invokes()}
    assert any(p >= 2 for p in procs)  # some process got bumped
    # pairing still works: thread = process % concurrency is sequential
    assert all(h.completion(op) is not None for op in h.invokes())


def test_concurrent_generator_keys():
    gen = independent.concurrent_generator(
        2, range(100),
        lambda k: limit(6, mix([r, w])),
    )
    h = run_gen(time_limit(20 * SECOND, gen), concurrency=4)
    invs = h.invokes()
    assert invs
    keys = {op.value[0] for op in invs}
    assert len(keys) >= 2  # 2 groups of 2 threads each, working in parallel
    # values are (k, v) tuples
    for op in invs:
        assert isinstance(op.value, tuple) and len(op.value) == 2
    # each key sees at most 6 invokes
    from collections import Counter
    per_key = Counter(op.value[0] for op in invs)
    assert all(c <= 6 for c in per_key.values())
    # subhistory unwraps
    k0 = sorted(keys)[0]
    sub = independent.subhistory(h, k0)
    assert sub and not isinstance(sub[0].value, tuple)


def test_sleep_gen_and_log():
    gen = phases(
        sleep_gen(1 * SECOND),
        log("hello"),
        limit(2, r),
    )
    h = run_gen(gen, concurrency=2)
    invs = h.invokes()
    assert len(invs) == 2
    assert all(op.time >= 1 * SECOND for op in invs)
    assert all(op.f != "log" for op in h)  # log ops not recorded


def test_determinism_full_stack():
    def once_run():
        gen = time_limit(3 * SECOND, stagger(int(0.02 * SECOND), mix([r, w])))
        return run_gen(gen, concurrency=4, seed=123).to_jsonl()

    assert once_run() == once_run()


def test_fngen_finite_source_no_loss():
    # Regression: a stateful fn source must not lose ops while threads busy.
    items = list(range(12))

    def src(test, ctx):
        return {"f": "item", "value": items.pop(0)} if items else None

    h = run_gen(src, concurrency=2, latency=int(0.2 * SECOND))
    vals = sorted(op.value for op in h.invokes())
    assert vals == list(range(12))


def test_explicit_process_busy_thread_no_loss():
    # Regression: ops pinned to a busy thread queue up instead of dropping.
    h = run_gen(limit(5, repeat({"f": "ping", "process": 0})), concurrency=2,
                latency=int(0.1 * SECOND))
    assert len([op for op in h.invokes() if op.f == "ping"]) == 5


def test_reserve_exact_thread_count_terminates():
    # Regression: reserve consuming all threads must terminate (no empty
    # default branch pending forever).
    gen = reserve(2, limit(4, repeat({"f": "a"})),
                  limit(4, repeat({"f": "b"})))
    h = run_gen(gen, concurrency=2)  # counts sum to concurrency... 2+default
    # here: 2 reserved for "a", default "b" gets zero threads -> branch
    # omitted; only "a" ops run
    assert len([op for op in h.invokes() if op.f == "a"]) == 4
    assert len([op for op in h.invokes() if op.f == "b"]) == 0


def test_queued_op_after_info_gets_fresh_process():
    # Regression: an op queued behind an op that completes :info must be
    # invoked by the *retired* process's successor, not the old process.
    calls = [0]

    async def crashy(process, op):
        calls[0] += 1
        await sleep(int(0.05 * SECOND))
        if calls[0] == 1:
            return op.evolve(type="info", error="timeout")
        return op.evolve(type="ok")

    h = run_gen(limit(3, repeat({"f": "w", "process": 0})), concurrency=2,
                invoke=crashy)
    invs = [op for op in h.invokes()]
    assert invs[0].process == 0
    assert all(op.process > 0 and op.process % 2 == 0 for op in invs[1:])
    # history stays well-formed (every invoke pairs)
    assert all(h.completion(op) is not None for op in invs)
