"""Unit coverage for the userspace proxy plane (net/proxy.py,
net/plane.py): one-way and bidirectional drops, attribution (fake
preamble and real-etcd X-Server-From), latency FIFO under jitter,
slow-close, bandwidth caps, dynamic rule flips on live connections,
and plane routing/heal semantics — all against a local echo server,
no cluster required."""

import socket
import threading
import time

import pytest

from jepsen_etcd_tpu.net.plane import NetPlane
from jepsen_etcd_tpu.net.proxy import PASS, PEER_PREAMBLE, LinkProxy

SHORT = 0.5   # recv timeout that proves "nothing arrived"


class EchoServer:
    """Echoes every byte back; closes its side on client EOF."""

    def __init__(self):
        self.srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", 0))
        self.srv.listen(16)
        self.port = self.srv.getsockname()[1]
        self._conns = []
        self._lock = threading.Lock()
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._echo, args=(conn,),
                             daemon=True).start()

    def _echo(self, conn):
        try:
            while True:
                data = conn.recv(65536)
                if not data:
                    break
                conn.sendall(data)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def close(self):
        try:
            self.srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


@pytest.fixture()
def echo():
    srv = EchoServer()
    yield srv
    srv.close()


@pytest.fixture()
def plane():
    pl = NetPlane(seed=7)
    yield pl
    pl.close()


def peer_conn(port, name="n2", payload=b""):
    """Dial a peer-kind proxy with the fake-etcd attribution preamble."""
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(PEER_PREAMBLE + name.encode() + b"\n" + payload)
    return s


def recv_exact(sock, n, timeout=5.0):
    sock.settimeout(timeout)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            break
        buf += chunk
    return buf


def assert_silent(sock, timeout=SHORT):
    sock.settimeout(timeout)
    with pytest.raises(TimeoutError):
        sock.recv(1)


# ---- routing table ---------------------------------------------------------

def test_route_semantics(plane):
    plane.nodes.update({"n1", "n2", "n3"})
    assert plane.route("n2", "n1", "peer") is PASS
    plane.partition_pairs({("n2", "n1"), frozenset(("n1", "n3"))})
    # ordered tuple: one direction only
    assert plane.route("n2", "n1", "peer").drop is True
    assert plane.route("n1", "n2", "peer").drop is False
    # frozenset: both directions
    assert plane.route("n1", "n3", "peer").drop is True
    assert plane.route("n3", "n1", "peer").drop is True
    # unattributed and client legs are never directionally dropped
    assert plane.route(None, "n1", "peer").drop is False
    assert plane.route("client", "n1", "client").drop is False
    plane.heal_partition()
    assert plane.route("n2", "n1", "peer") is PASS


def test_partition_groups_cross_block(plane):
    plane.nodes.update({"n1", "n2", "n3", "n4", "n5"})
    plane.partition([["n1", "n2"], ["n3", "n4", "n5"]])
    assert plane.route("n1", "n3", "peer").drop is True
    assert plane.route("n4", "n2", "peer").drop is True
    assert plane.route("n1", "n2", "peer").drop is False
    assert plane.route("n3", "n5", "peer").drop is False
    stats = plane.stats()
    assert stats["blocked"] == 6  # 2x3 cross pairs
    plane.heal()
    assert plane.stats()["blocked"] == 0


# ---- one-way and bidirectional drops ---------------------------------------

def test_one_way_drop_blocks_only_that_direction(echo, plane):
    port = plane.front("n1", "peer", echo.port)
    # baseline: attributed conn echoes (preamble is forwarded too)
    s = peer_conn(port, "n2", b"hello")
    want = PEER_PREAMBLE + b"n2\nhello"
    assert recv_exact(s, len(want)) == want

    # block n2 -> n1: upstream bytes blackhole, nothing echoes back
    plane.partition_pairs({("n2", "n1")})
    s.sendall(b"dropped?")
    assert_silent(s)

    # the reverse direction alone: upstream flows, the ECHO blackholes
    plane.partition_pairs({("n1", "n2")})
    s2 = peer_conn(port, "n2", b"reverse")
    assert_silent(s2)

    # heal: the SAME long-lived connection flows again (per-chunk
    # rule consultation, no reconnect needed)
    plane.heal_partition()
    s.sendall(b"back")
    assert recv_exact(s, len(b"back")) == b"back"
    s.close()
    s2.close()


def test_bidirectional_drop_and_client_immunity(echo, plane):
    ppeer = plane.front("n1", "peer", echo.port)
    pcli = plane.front("n1", "client", echo.port)
    plane.partition_pairs({frozenset(("n1", "n2"))})
    s = peer_conn(ppeer, "n2", b"x")
    assert_silent(s)
    # client legs never partition-drop: clients reach their own node
    c = socket.create_connection(("127.0.0.1", pcli), timeout=5)
    c.sendall(b"client-bytes")
    assert recv_exact(c, len(b"client-bytes")) == b"client-bytes"
    s.close()
    c.close()


def test_unattributed_peer_conn_never_dropped(echo, plane):
    port = plane.front("n1", "peer", echo.port)
    plane.partition_pairs({("n2", "n1"), frozenset(("n1", "n2")),
                           frozenset(("n1", "n3"))})
    # a full HTTP header block with no X-Server-From: src=None
    req = b"GET /raft HTTP/1.1\r\nHost: n1\r\n\r\n"
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(req)
    assert recv_exact(s, len(req)) == req
    s.close()


def test_x_server_from_attribution(echo, plane):
    """Real-etcd rafthttp attribution: the member-id hex in
    X-Server-From maps to a name via register_member_ids, and the
    attributed conn obeys directional drops."""
    port = plane.front("n1", "peer", echo.port)
    plane.register_member_ids({"8E9E05C52164694D": "n2"})
    plane.partition_pairs({("n2", "n1")})
    req = (b"POST /raft/stream HTTP/1.1\r\nHost: n1\r\n"
           b"X-Server-From: 8e9e05c52164694d\r\n\r\n")
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(req)
    assert_silent(s)
    # an unknown member id resolves to None -> passes through
    req2 = (b"POST /raft/stream HTTP/1.1\r\nHost: n1\r\n"
            b"X-Server-From: feedfacedeadbeef\r\n\r\n")
    s2 = socket.create_connection(("127.0.0.1", port), timeout=5)
    s2.sendall(req2)
    assert recv_exact(s2, len(req2)) == req2
    s.close()
    s2.close()


# ---- latency / bandwidth / slow-close --------------------------------------

def test_latency_floor_and_fifo_under_jitter(echo, plane):
    port = plane.front("n1", "client", echo.port)
    plane.set_latency(delta_ms=40, jitter_ms=30)
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    t0 = time.monotonic()
    msgs = [b"msg-%d|" % i for i in range(5)]
    for m in msgs:
        s.sendall(m)
        time.sleep(0.01)
    want = b"".join(msgs)
    got = recv_exact(s, len(want))
    elapsed = time.monotonic() - t0
    # FIFO: one pump thread per direction sleeps inline, so jitter
    # cannot reorder delivery
    assert got == want
    # the floor: at least one chunk each way paid >= delta
    assert elapsed >= 0.08, elapsed
    plane.clear_latency()
    # cleared: a round trip is fast again
    t0 = time.monotonic()
    s.sendall(b"fast")
    assert recv_exact(s, 4) == b"fast"
    assert time.monotonic() - t0 < 1.0
    s.close()


def test_bandwidth_cap_serialization_delay(echo, plane):
    port = plane.front("n1", "client", echo.port)
    plane.set_bandwidth(256 * 1024)  # bytes/s
    payload = b"\xab" * (64 * 1024)  # 0.25 s per direction at the cap
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    t0 = time.monotonic()
    s.sendall(payload)
    got = recv_exact(s, len(payload), timeout=10)
    elapsed = time.monotonic() - t0
    assert got == payload
    assert elapsed >= 0.25, elapsed
    s.close()


def test_slow_close_delays_fin_propagation(echo, plane):
    port = plane.front("n1", "client", echo.port)
    plane.set_slow_close(0.3)
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(b"bye")
    assert recv_exact(s, 3) == b"bye"
    t0 = time.monotonic()
    s.shutdown(socket.SHUT_WR)
    # EOF must cross upstream (0.3 s hold), bounce off the echo
    # server's close, and cross back (another hold)
    s.settimeout(10)
    while True:
        if s.recv(4096) == b"":
            break
    assert time.monotonic() - t0 >= 0.3
    s.close()


# ---- lossy links (drop_prob) -----------------------------------------------

def test_drop_prob_route_clamp_and_heal(plane):
    assert plane.route("client", "n1", "client") is PASS
    plane.set_drop_prob(0.25)
    # loss applies to every leg, client and peer alike (netem-on-the-
    # interface semantics, unlike directional partition drops)
    assert plane.route("client", "n1", "client").drop_prob == 0.25
    assert plane.route("n2", "n1", "peer").drop_prob == 0.25
    assert plane.stats()["drop_prob"] == 0.25
    plane.clear_drop_prob()
    assert plane.route("client", "n1", "client") is PASS
    plane.set_drop_prob(1.5)  # clamped
    assert plane.stats()["drop_prob"] == 1.0
    plane.heal()
    assert plane.stats()["drop_prob"] == 0.0
    assert plane.route("client", "n1", "client") is PASS


class _SinkSock:
    """Records what _forward lets through; never blocks."""

    def __init__(self):
        self.chunks = []

    def sendall(self, data):
        self.chunks.append(data)


def _drop_pattern(seed, n=64):
    """The per-chunk pass/drop pattern a fresh plane with this seed
    produces for a fixed chunk sequence (driving _forward directly:
    TCP chunk coalescing never enters, so the pattern is a pure
    function of the seed)."""
    plane = NetPlane(seed=seed)
    plane.set_drop_prob(0.5)
    proxy = LinkProxy("n1", "client", target_port=1,
                      router=plane.route, jitter=plane._jitter)
    try:
        wsock = _SinkSock()
        state = {}
        pattern = []
        for i in range(n):
            before = len(wsock.chunks)
            proxy._forward(b"chunk-%d" % i, wsock, "client", "n1", state)
            pattern.append(len(wsock.chunks) > before)
        return pattern
    finally:
        proxy.close()
        plane.close()


def test_drop_prob_seeded_determinism():
    a = _drop_pattern(seed=7)
    b = _drop_pattern(seed=7)
    assert a == b, "same seed must reproduce the same loss pattern"
    assert any(a) and not all(a), "p=0.5 over 64 chunks: both outcomes"
    c = _drop_pattern(seed=8)
    assert a != c, "a different seed draws a different pattern"


def test_drop_prob_end_to_end_and_recovery(echo, plane):
    """p=1.0 loses every chunk while the connection stays up; clearing
    the rule restores the SAME connection (per-chunk consultation)."""
    port = plane.front("n1", "client", echo.port)
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(b"before")
    assert recv_exact(s, len(b"before")) == b"before"
    plane.set_drop_prob(1.0)
    s.sendall(b"lost")
    assert_silent(s)
    plane.clear_drop_prob()
    s.sendall(b"after")
    assert recv_exact(s, len(b"after")) == b"after"
    s.close()


# ---- lifecycle -------------------------------------------------------------

def test_dead_upstream_counts_dropped_conn(plane):
    """Fronting a dead port: the dial fails, the client sees EOF/reset,
    the proxy survives for the next connection."""
    dead = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    dead.bind(("127.0.0.1", 0))
    dead_port = dead.getsockname()[1]
    dead.close()  # nothing listens here now
    port = plane.front("n1", "client", dead_port)
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(5)
    try:
        assert s.recv(1) == b""
    except OSError:
        pass  # ECONNRESET is as good as EOF here
    s.close()


def test_plane_close_is_idempotent(echo, plane):
    plane.front("n1", "client", echo.port)
    plane.front("n1", "peer", echo.port)
    assert plane.stats()["links"] == 2
    plane.close()
    plane.close()
