"""graftlint: per-family fixture tests, suppression/baseline
machinery, and the tier-1 gate asserting the tree itself is clean.

Fixture snippets lint under ``Policy(all_in_scope=True)`` — every file
columnar, every def entry-reachable, no wall-clock allowlist — so each
rule can fire on a bare tmp file without path gymnastics.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from jepsen_etcd_tpu.lint import Policy, run_lint
from jepsen_etcd_tpu.lint.engine import write_baseline
from jepsen_etcd_tpu.lint.rules import ALL_RULES, select

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TEL_REGISTRY = {"spans": ("phase:*", "good.span"),
                "counters": ("a.b", "stream.*_reuse"),
                "events": ("boom",),
                "hists": ("good.hist", "lat.*")}


def lint_snippet(tmp_path, source, name="snippet.py", rules=None,
                 baseline_path=None):
    f = tmp_path / name
    f.write_text(source)
    return run_lint(paths=[str(f)], rules=rules,
                    baseline_path=baseline_path,
                    policy=Policy(all_in_scope=True,
                                  tel_registry=TEL_REGISTRY),
                    root=str(tmp_path))


def rules_fired(report):
    return {f.rule for f in report.findings if not f.suppressed}


# -- DET ---------------------------------------------------------------------

def test_det001_wall_clock_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "import time\n"
        "def stamp():\n"
        "    return time.time()\n"))
    assert "DET001" in rules_fired(r)


def test_det001_virtual_clock_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "def stamp(loop):\n"
        "    return loop.now()\n"))
    assert "DET001" not in rules_fired(r)


def test_det002_unseeded_random_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "import random\n"
        "def draw():\n"
        "    return random.random()\n"))
    assert "DET002" in rules_fired(r)


def test_det002_seeded_instance_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "import random\n"
        "def draw(seed):\n"
        "    return random.Random(seed).random()\n"))
    assert "DET002" not in rules_fired(r)


def test_det003_set_iteration_and_id(tmp_path):
    r = lint_snippet(tmp_path, (
        "def order(xs, y):\n"
        "    out = list(set(xs))\n"
        "    for v in set(xs) | {1}:\n"
        "        out.append(v)\n"
        "    return out, id(y)\n"))
    assert sum(f.rule == "DET003" for f in r.findings) == 3


def test_det003_sorted_set_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "def order(xs):\n"
        "    return sorted(set(xs))\n"))
    assert "DET003" not in rules_fired(r)


# -- COL ---------------------------------------------------------------------

def test_col001_ops_materialization_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "def rows(h):\n"
        "    return [op for op in h.ops] + h.to_ops()\n"))
    assert sum(f.rule == "COL001" for f in r.findings) == 2


def test_col002_dict_api_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "def bands(h):\n"
        "    return [h.completion(op) for op in h.nemesis_ops()]\n"))
    assert sum(f.rule == "COL002" for f in r.findings) == 2


def test_col_columnar_accessors_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "def rows(cols):\n"
        "    return cols.client_pairs(), cols.time.tolist()\n"))
    assert not {"COL001", "COL002"} & rules_fired(r)


def test_col_scoped_to_columnar_modules(tmp_path):
    # default policy: only policy.COLUMNAR paths are in scope
    f = tmp_path / "plain.py"
    f.write_text("def rows(h):\n    return h.ops\n")
    r = run_lint(paths=[str(f)], baseline_path=None,
                 policy=Policy(), root=str(tmp_path))
    assert "COL001" not in rules_fired(r)


# -- JAX ---------------------------------------------------------------------

def test_jax001_loop_dispatch_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "import jax.numpy as jnp\n"
        "def walk(x):\n"
        "    for _ in range(8):\n"
        "        x = jnp.add(x, 1)\n"
        "    return x\n"))
    assert "JAX001" in rules_fired(r)


def test_jax001_jitted_loop_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def walk(x):\n"
        "    for _ in range(8):\n"
        "        x = jnp.add(x, 1)\n"
        "    return x\n"))
    assert "JAX001" not in rules_fired(r)


def test_jax001_factory_kernel_clean(tmp_path):
    # pallas_call(_make_kernel(...)) traces the returned inner def
    r = lint_snippet(tmp_path, (
        "import jax.numpy as jnp\n"
        "from jax.experimental.pallas import pallas_call\n"
        "def _make_kernel(n):\n"
        "    def kernel(ref):\n"
        "        for i in range(n):\n"
        "            ref[i] = jnp.add(ref[i], 1)\n"
        "    return kernel\n"
        "call = pallas_call(_make_kernel(4))\n"))
    assert "JAX001" not in rules_fired(r)


def test_jax002_transfer_in_loop_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "import numpy as np\n"
        "def collect(devs):\n"
        "    out = []\n"
        "    for d in devs:\n"
        "        out.append(np.asarray(d))\n"
        "    return out\n"))
    assert "JAX002" in rules_fired(r)


def test_jax003_jit_per_call_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "import jax\n"
        "def run(x):\n"
        "    return jax.jit(lambda v: v + 1)(x)\n"))
    assert "JAX003" in rules_fired(r)


def test_jax003_cached_jit_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "import functools\n"
        "import jax\n"
        "@functools.lru_cache(maxsize=None)\n"
        "def kernel(n):\n"
        "    return jax.jit(lambda v: v + n)\n"))
    assert "JAX003" not in rules_fired(r)


def test_jax004_float64_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "import jax.numpy as jnp\n"
        "def zeros(n):\n"
        "    return jnp.zeros(n, dtype='float64')\n"))
    assert "JAX004" in rules_fired(r)


# -- THR ---------------------------------------------------------------------

_THR_RACY = """\
import threading

class Feed:
    def __init__(self):
        self.rows = 0
        self._cv = threading.Condition()
        self._t = threading.Thread(target=self._worker)

    def _worker(self):
        self.rows += 1
"""


def test_thr001_unlocked_write_fires(tmp_path):
    r = lint_snippet(tmp_path, _THR_RACY)
    assert "THR001" in rules_fired(r)


def test_thr001_locked_write_clean(tmp_path):
    r = lint_snippet(tmp_path, _THR_RACY.replace(
        "        self.rows += 1",
        "        with self._cv:\n            self.rows += 1"))
    assert "THR001" not in rules_fired(r)


def test_thr002_global_rebind_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "import threading\n"
        "N = 0\n"
        "def _worker():\n"
        "    global N\n"
        "    N += 1\n"
        "t = threading.Thread(target=_worker)\n"))
    assert "THR002" in rules_fired(r)


# -- TEL ---------------------------------------------------------------------

def test_tel001_unentered_span_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "def trace(tel):\n"
        "    tel.span('good.span')\n"))
    assert "TEL001" in rules_fired(r)


def test_tel001_with_span_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "def trace(tel):\n"
        "    with tel.span('good.span'):\n"
        "        pass\n"))
    assert "TEL001" not in rules_fired(r)


def test_tel002_unregistered_name_fires(tmp_path):
    r = lint_snippet(tmp_path, (
        "def bump(tel):\n"
        "    tel.counter('a.typo')\n"))
    assert "TEL002" in rules_fired(r)


def test_tel002_wildcard_and_prefix_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "def bump(tel, name):\n"
        "    tel.counter('a.b')\n"
        "    tel.counter(f'stream.{name}_reuse')\n"
        "    with tel.span('phase:setup'):\n"
        "        pass\n"))
    assert "TEL002" not in rules_fired(r)


def test_tel002_hist_names_checked(tmp_path):
    r = lint_snippet(tmp_path, (
        "def fold(tel, vals):\n"
        "    tel.hist('h.typo', 1.0)\n"
        "    tel.hist_many('h.typo2', vals)\n"))
    assert "TEL002" in rules_fired(r)


def test_tel002_registered_hist_clean(tmp_path):
    r = lint_snippet(tmp_path, (
        "def fold(tel, vals, f):\n"
        "    tel.hist('good.hist', 1.0)\n"
        "    tel.hist_many(f'lat.{f}', vals)\n"))
    assert "TEL002" not in rules_fired(r)


def test_tel_re_match_span_not_confused(tmp_path):
    # re.Match.span(1) has no string arg: not the telemetry signature
    r = lint_snippet(tmp_path, (
        "import re\n"
        "def where(m):\n"
        "    return m.span(1)\n"))
    assert not {"TEL001", "TEL002"} & rules_fired(r)


# -- suppressions ------------------------------------------------------------

def test_suppression_with_reason_silences(tmp_path):
    r = lint_snippet(tmp_path, (
        "import random\n"
        "def draw():\n"
        "    # graftlint: ignore[DET002] fixture exercises the grammar\n"
        "    return random.random()\n"))
    assert not r.errors
    assert any(f.rule == "DET002" and f.suppressed for f in r.findings)


def test_suppression_inline_and_family(tmp_path):
    r = lint_snippet(tmp_path, (
        "import random\n"
        "def draw():\n"
        "    return random.random()  "
        "# graftlint: ignore[DET] family-wide fixture\n"))
    assert not r.errors


def test_suppression_without_reason_is_lint002(tmp_path):
    r = lint_snippet(tmp_path, (
        "import random\n"
        "def draw():\n"
        "    return random.random()  # graftlint: ignore[DET002]\n"))
    assert {f.rule for f in r.errors} == {"LINT002"}


def test_orphan_suppression_is_lint001(tmp_path):
    r = lint_snippet(tmp_path, (
        "def clean():\n"
        "    # graftlint: ignore[DET002] nothing fires here\n"
        "    return 1\n"))
    assert {f.rule for f in r.errors} == {"LINT001"}


# -- baseline ----------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    src = ("import random\n"
           "def draw():\n"
           "    return random.random()\n")
    bl = tmp_path / "baseline.json"
    first = lint_snippet(tmp_path, src)
    assert first.errors
    write_baseline(str(bl), first.findings)
    # grandfathered: same findings, zero errors
    again = lint_snippet(tmp_path, src, baseline_path=str(bl))
    assert not again.errors
    assert any(f.baselined for f in again.findings)
    # finding fixed: the stale entry must flag LINT004
    fixed = lint_snippet(tmp_path, (
        "import random\n"
        "def draw(seed):\n"
        "    return random.Random(seed).random()\n"),
        baseline_path=str(bl))
    assert {f.rule for f in fixed.errors} == {"LINT004"}


# -- selection ---------------------------------------------------------------

def test_select_by_family_and_id():
    fams = {f.FAMILY for f in select(["DET"])}
    assert fams == {"DET"}
    fams = {f.FAMILY for f in select(["col001", "TEL"])}
    assert fams == {"COL", "TEL"}
    with pytest.raises(ValueError):
        select(["NOPE999"])
    assert len(ALL_RULES) == 13


def test_rule_filter_scopes_findings(tmp_path):
    # selection is family-granular: asking for DET002 runs the DET
    # family and nothing else
    r = lint_snippet(tmp_path, (
        "import random\n"
        "def draw(tel):\n"
        "    tel.counter('a.typo')\n"
        "    return random.random()\n"),
        rules=["DET002"])
    fired = rules_fired(r)
    assert "DET002" in fired
    assert all(rule.startswith("DET") for rule in fired)


# -- the tier-1 gate ---------------------------------------------------------

def test_tree_is_lint_clean():
    """THE gate: the shipped tree has zero non-suppressed,
    non-baselined findings. A regression anywhere in the five families
    (or an orphaned suppression, or a stale baseline entry) fails
    tier-1 here."""
    report = run_lint(root=REPO)
    msgs = [f"{f.location()}: {f.rule}: {f.message}"
            for f in report.errors]
    assert not msgs, "\n".join(msgs)
    assert report.files > 50  # the whole package was actually scanned


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, "-m", "jepsen_etcd_tpu.lint", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    data = json.loads(out.stdout)
    assert data["errors"] == 0
    bad = tmp_path / "bad.py"
    bad.write_text("import random\nx = random.random()\n")
    out = subprocess.run(
        [sys.executable, "-m", "jepsen_etcd_tpu.lint", str(bad)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 1
    assert "DET002" in out.stdout
