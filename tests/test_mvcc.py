"""MVCC consistency surfaces (ISSUE 18): bounded staleness, snapshot
ranges, lease churn, compaction-vs-watch.

Two regression walls around the new subsystem:

- **Injection pins**: each checker verdict class is tested against the
  one simbatch injection that seeds its bug (engine.py ``inject_*``
  hooks). Flag on → every seed fails with EXACTLY that class; flag
  off → every seed passes. A checker that goes soft (misses its bug)
  or trigger-happy (new classes leak in) fails here, not in the field.
- **Cross-epoch verdict equality**: the same cell judged on an
  epoch-v1 (SimLoop event loop) history and an epoch-v2 (batched
  lockstep) history must produce the same surface verdict — the
  consistency claims are properties of the protocol semantics, not of
  which generator produced the history. One lean cell per workload
  runs in tier-1; the full workload × nemesis sweep is ``slow``.
"""

import pytest

from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.shrink import checker_opts_from
from jepsen_etcd_tpu.runner.test_runner import run_test
from jepsen_etcd_tpu.simbatch import BatchConfig, generate
from jepsen_etcd_tpu.workloads import workloads

#: workload -> its surface checker's key in the composed result
SURFACE_KEYS = {"register-stale": "staleness", "ranges": "ranges",
                "lock-lease": "lease", "compact-watch": "watch-mvcc"}

#: workload -> (engine injection flag, the ONE verdict class it pins)
INJECTIONS = {
    "register-stale": ("inject_stale_snapshot", "stale-beyond-bound"),
    "ranges": ("inject_torn_range", "torn-range"),
    "lock-lease": ("inject_double_grant", "double-grant"),
    "compact-watch": ("inject_compaction_swallow", "lost-event"),
}


def _v2_opts(wl: str, **kw) -> dict:
    o = {"workload": wl, "nodes": ["n1", "n2", "n3"], "concurrency": 8,
         "rate": 200.0, "time_limit": 2.0, "gen_epoch": "epoch-v2"}
    if wl == "register-stale":
        # tight bound so a frozen-replica lag is beyond-bound within
        # the short run (the default 8 s would excuse everything here)
        o["staleness_bound_s"] = 0.5
    o.update(kw)
    return o


def _v2_verdicts(opts: dict, seeds) -> list:
    """Cheap epoch-v2 evaluations: batched generation + the composed
    workload checker, no store, no test runner."""
    cfg = BatchConfig.from_opts(opts)
    copts = checker_opts_from(opts)
    checker = workloads()[cfg.workload](dict(copts))["checker"]
    g = generate(cfg, list(seeds))
    return [checker.check(dict(copts), h) for h in g["histories"]]


def _surface_verdict(sub: dict) -> tuple:
    classes = sorted({v["class"] for v in sub.get("violations", ())})
    return sub["valid?"], tuple(classes)


@pytest.mark.parametrize("wl", sorted(SURFACE_KEYS))
def test_injected_bug_trips_exactly_its_class(wl):
    """Flag off: all 8 seeds pass. Flag on: all 8 seeds fail with the
    pinned class and nothing else — the injection is definite for its
    checker, and the checker convicts only its own bug."""
    flag, klass = INJECTIONS[wl]
    key = SURFACE_KEYS[wl]
    seeds = range(8)
    for r in _v2_verdicts(_v2_opts(wl), seeds):
        assert r["valid?"] is True, (wl, r[key])
    for r in _v2_verdicts(_v2_opts(wl, **{flag: True}), seeds):
        assert r["valid?"] is False
        sub = r[key]
        assert sub["valid?"] is False
        classes = {v["class"] for v in sub["violations"]}
        assert classes == {klass}, (wl, classes)


def test_injections_are_isolated_per_surface():
    """A foreign injection must not convict a bystander surface: the
    torn-range bug runs under the register-stale workload's checker
    (and vice versa) without tripping it."""
    for r in _v2_verdicts(_v2_opts("register-stale",
                                   inject_torn_range=True), range(4)):
        assert r["valid?"] is True, r["staleness"]
    for r in _v2_verdicts(_v2_opts("ranges",
                                   inject_stale_snapshot=True),
                          range(4)):
        assert r["valid?"] is True, r["ranges"]


# -- cross-epoch verdict equality -----------------------------------------

#: epoch-v1 faults start after compose's 5 virtual-second grace sleep,
#: so time_limit must leave room for real fault windows
_V1_BASE = {"nodes": ["n1", "n2", "n3"], "concurrency": 6,
            "time_limit": 12, "rate": 100.0, "nemesis_interval": 3,
            "seed": 5}

#: lean tier-1 slice: every workload once, faults on two of them
CELLS_TIER1 = [("register-stale", ()), ("ranges", ("kill",)),
               ("lock-lease", ("partition",)), ("compact-watch", ())]

#: the rest of workloads x {none, kill, partition}
CELLS_FULL = [(wl, nem)
              for wl in sorted(SURFACE_KEYS)
              for nem in ((), ("kill",), ("partition",))
              if (wl, nem) not in CELLS_TIER1]


def _cross_epoch_cell(tmp_path, wl, nem):
    key = SURFACE_KEYS[wl]
    base = dict(_V1_BASE, workload=wl, nemesis=list(nem),
                store_base=str(tmp_path))
    v1 = run_test(etcd_test(dict(base)))["results"]["workload"][key]
    v2 = _v2_verdicts(dict(base, gen_epoch="epoch-v2"),
                      [base["seed"]])[0][key]
    assert _surface_verdict(v1) == _surface_verdict(v2), (wl, nem, v1, v2)
    # the new workloads are expected-to-pass across the fault matrix
    assert v1["valid?"] is True, (wl, nem, v1)


@pytest.mark.parametrize("wl,nem", CELLS_TIER1)
def test_cross_epoch_verdict_equality(tmp_path, wl, nem):
    _cross_epoch_cell(tmp_path, wl, nem)


@pytest.mark.slow
@pytest.mark.parametrize("wl,nem", CELLS_FULL)
def test_cross_epoch_verdict_equality_full(tmp_path, wl, nem):
    _cross_epoch_cell(tmp_path, wl, nem)


def test_aggregate_grows_consistency_surface_column(tmp_path):
    """/aggregate surfaces the MVCC checker verdicts as their own
    column: per-surface badges with violation counts for runs that
    composed a surface checker, an em-dash for runs that didn't."""
    import json
    import os

    from jepsen_etcd_tpu.serve import aggregate_html

    def fake_run(name, results):
        rdir = os.path.join(str(tmp_path), name, "0001")
        os.makedirs(rdir)
        open(os.path.join(rdir, "history.jsonl"), "w").close()
        with open(os.path.join(rdir, "results.json"), "w") as f:
            json.dump(results, f)

    fake_run("surfaced", {
        "valid?": False,
        "workload": {"valid?": False,
                     "staleness": {"valid?": False,
                                   "violation-count": 3},
                     "lease": {"valid?": True}}})
    fake_run("plain", {"valid?": True, "workload": {"valid?": True}})
    page = aggregate_html(str(tmp_path))
    assert "consistency" in page
    assert "stale&nbsp;" in page and "(3)" in page
    assert "lease&nbsp;" in page


# -- proc==session lease assumption (ISSUE 19 satellite) --------------------


def _lease_cols(rows):
    """Hand-built OpColumns over an acquire/release f_table; rows are
    (type_code, proc, f_name, time)."""
    import numpy as np

    from jepsen_etcd_tpu.core.history import OpColumns

    ft = ["acquire", "release"]
    n = len(rows)
    return OpColumns(
        np.array([r[0] for r in rows], np.int8),
        np.array([ft.index(r[2]) for r in rows], np.int32),
        np.array([r[1] for r in rows], np.int64),
        np.zeros(n, np.int64),
        np.array([r[3] for r in rows], np.int64),
        np.arange(n), [None] * n, {}, {}, ft, ["k"], [])


def test_lease_sessions_assert_proc_is_session():
    """The lease walk's load-bearing assumption (core/mvcc.py
    docstring): one proc never holds two leases. The legitimate
    acquire/release alternation both sim epochs emit walks fine; a
    same-proc re-acquire — what a live etcd lease id can do — raises
    the diagnostic instead of silently merging two leases into one
    session span."""
    from jepsen_etcd_tpu.core.mvcc import _lease_sessions

    ok = _lease_cols([(0, 0, "acquire", 1), (1, 0, "acquire", 2),
                      (0, 0, "release", 3), (0, 0, "acquire", 4),
                      (1, 0, "acquire", 5)])
    sess = _lease_sessions(ok)
    assert [s[1] for s in sess] == [0, 0]
    assert sess[0][4] == 3 and sess[1][4] is None

    bad = _lease_cols([(0, 0, "acquire", 1), (1, 0, "acquire", 2),
                       (0, 0, "acquire", 3), (1, 0, "acquire", 4)])
    with pytest.raises(ValueError, match="proc==session"):
        _lease_sessions(bad)
