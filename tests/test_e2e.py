"""End-to-end runs through the composed test harness (the minimum slice:
register workload against the simulated cluster, SURVEY §7 step 5)."""

import pytest

from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.test_runner import run_test


def run(tmp_path, **opts):
    base = {"time_limit": 6, "rate": 50, "ops_per_key": 30,
            "store_base": str(tmp_path), "seed": 7}
    base.update(opts)
    return run_test(etcd_test(base))


def test_register_linearizable_passes(tmp_path):
    out = run(tmp_path, workload="register")
    assert out["valid?"] is True
    assert len(out["history"]) > 100
    wl = out["results"]["workload"]
    assert wl["key-count"] >= 1


def test_register_serializable_fails(tmp_path):
    # Stale node-local reads are NOT linearizable; the checker must catch it.
    out = run(tmp_path, workload="register", serializable=True, rate=100,
              time_limit=8)
    assert out["valid?"] is False


def test_register_etcdctl_backend(tmp_path):
    out = run(tmp_path, workload="register", client_type="etcdctl")
    assert out["valid?"] is True


def test_none_workload(tmp_path):
    out = run(tmp_path, workload="none", time_limit=3)
    assert out["valid?"] is True


def test_run_determinism(tmp_path):
    h1 = run(tmp_path, workload="register", seed=42)["history"].to_jsonl()
    h2 = run(tmp_path, workload="register", seed=42)["history"].to_jsonl()
    assert h1 == h2


def test_artifacts_written(tmp_path):
    out = run(tmp_path, workload="register")
    d = out["dir"]
    import os
    for f in ("history.jsonl", "results.json", "test.json", "timeline.html",
              "latency-raw.png", "rate.png", "n1/etcd.log"):
        assert os.path.exists(os.path.join(d, f)), f


def test_hot_key_fault_churn_stays_linearizable(tmp_path):
    """One hot key through kill+partition churn — the configuration
    class that exposed the r5 new-leader stale-read raft bug (found by
    this harness's own checkers at 240 sim-s; the exact mechanism has
    a deterministic unit test in test_sut.py). This CI-scale run
    guards the broader invariant: a single key absorbing every write
    across repeated elections must stay linearizable."""
    out = run(tmp_path, nemesis=["kill", "partition"],
              nemesis_interval=8.0, ops_per_key=100_000,
              time_limit=60, rate=300, seed=23)
    assert out["valid?"] is True, out.get("results", {}).get("workload")
