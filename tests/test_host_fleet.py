"""Multi-host checker fleet e2e (runner/host_agent.py + the TCP
checker service): the ISSUE 16 acceptance bars.

- A 2-host campaign in CI: separate worker-agent processes over
  loopback TCP, every run checked via the driver host's service, and
  the shipped==submitted ledger balancing ACROSS hosts — per host and
  in total — with verdict bit-identity vs in-process re-checks.
- The fleet surviving its own medicine: host<->service traffic routed
  through the net/ proxy plane under partitions, latency, lossy links
  and slow-close, with every check either retried to success or
  gracefully degraded (None -> local fallback), verdicts bit-identical
  throughout, and no permanent client latch.
- Agent death re-queues specs (capped), stranded specs run inline:
  a campaign always completes.
"""

import json
import os
import socket
import threading
import time

import pytest

from jepsen_etcd_tpu.net.plane import NetPlane
from jepsen_etcd_tpu.ops import wgl
from jepsen_etcd_tpu.runner import checker_service as svc_mod
from jepsen_etcd_tpu.runner import telemetry, transport
from jepsen_etcd_tpu.runner.campaign import campaign_specs, run_campaign
from jepsen_etcd_tpu.runner.host_agent import HostAgentPool
from jepsen_etcd_tpu.runner.telemetry import Telemetry

from test_campaign import PROJECTION, _recheck_locally
from test_checker_service import make_packs, view


# -- the 2-host campaign acceptance bar --------------------------------------

def _assert_cross_host_ledger(summary, hosts):
    """The shipped==submitted identity, extended across hosts: rows'
    producer-side fold per host == the service's consumer-side
    service.host_submitted.<host> series, and the totals balance."""
    rows = summary["runs"]
    ctr = (summary["telemetry"].get("counters") or {})
    by_host = summary["hosts"]
    assert by_host is not None and set(by_host) == set(hosts), by_host
    assert {r["host"] for r in rows} == set(hosts)
    submitted = ctr.get("service.submitted", 0)
    assert submitted >= len(rows), ctr  # every run shipped >= 1 pack
    total_shipped = 0
    for h in hosts:
        st = by_host[h]
        assert st["runs"] == sum(1 for r in rows if r["host"] == h)
        assert st["shipped"] == sum(r["service_shipped"] for r in rows
                                    if r["host"] == h)
        assert st["shipped"] == ctr.get(
            "service.host_submitted." + h), (h, st, ctr)
        total_shipped += st["shipped"]
    assert total_shipped == submitted, (total_shipped, ctr)
    assert not ctr.get("service.fallback"), ctr


def test_two_host_campaign_cross_host_ledger(tmp_path):
    """ISSUE 16 acceptance: a campaign fanned across two worker-agent
    processes (loopback TCP), every run checking via the driver's TCP
    service with the campaign's shared-secret token — cross-host
    ledger balanced, verdicts bit-identical to in-process re-checks."""
    base = {"time_limit": 1, "rate": 100.0, "force_kernel": True,
            "nodes": ["n1", "n2", "n3"]}
    specs = campaign_specs(base, ["register"], [[]],
                           runs_per_cell=8, seed0=200)
    summary = run_campaign(specs, pool=0, service=True,
                           service_tick_s=0.05,
                           hosts=["hostA", "hostB"],
                           store_base=str(tmp_path), name="fleet")
    assert summary["valid?"] is True, summary["failures"]
    rows = summary["runs"]
    assert len(rows) == 8
    assert all(r["status"] == "done" and r["valid"] is True
               for r in rows)
    ctr = (summary["telemetry"].get("counters") or {})
    assert ctr.get("campaign.hosts") == 2, ctr
    # both hosts actually worked (the queue is shared, the split need
    # not be even — but neither agent may starve completely)
    assert all(summary["hosts"][h]["runs"] >= 1
               for h in ("hostA", "hostB")), summary["hosts"]
    assert summary["agent_requeues"] == 0
    _assert_cross_host_ledger(summary, ["hostA", "hostB"])
    # verdict bit-identity: what the remote host shipped through the
    # service == what this process computes from the stored history
    for r in rows:
        stored = json.load(
            open(os.path.join(r["dir"], "results.json")))
        got = {str(k): {f: (v.get("linear") or {}).get(f)
                        for f in PROJECTION}
               for k, v in stored["workload"]["results"].items()}
        assert got == _recheck_locally(r["dir"]), r["dir"]
    # the aggregate dashboard renders the cross-host ledger join
    from jepsen_etcd_tpu.serve import aggregate_html
    page = aggregate_html(str(tmp_path))
    assert "ledger" in page and "balanced" in page, "hosts column missing"


# -- the fleet under its own faults ------------------------------------------

def test_fleet_survives_net_faults_through_proxy(monkeypatch):
    """Route host->service traffic through the net/ proxy plane and
    inject the SUT's own fault vocabulary: partition, latency+jitter,
    slow-close, lossy link. Every check either succeeds with a
    bit-identical verdict or degrades to None (the caller's local
    fallback) — fast, never a 600s blind wait — and the client always
    re-promotes after heal (no permanent latch)."""
    monkeypatch.setattr(svc_mod, "RETRY_BASE_S", 0.05)
    monkeypatch.setattr(svc_mod, "RETRY_CAP_S", 0.2)
    svc = svc_mod.CheckerService(tick_s=0.01, tcp=True,
                                 auth_token="tok",
                                 heartbeat_s=0.25).start()
    plane = NetPlane(seed=3)
    tel = Telemetry()
    prev = telemetry.current()
    telemetry.set_current(tel)
    client = None
    try:
        _, port = transport.parse_tcp(svc.tcp_endpoint)
        ep = plane.front_service(port)
        # idle_timeout >> heartbeat_s: silence means dead, not slow
        client = svc_mod.CheckerClient(ep, token="tok", host="hostB",
                                       connect_timeout=2.0,
                                       idle_timeout=1.5, timeout=60.0)
        packs = make_packs(301, 3, info_rate=0.2)
        want = [view(wgl.check_packed(p)) for p in packs]

        def check_ok():
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                outs = client.check(packs)
                if outs is not None:
                    return outs
                time.sleep(0.05)  # cooldown armed: wait it out
            raise AssertionError("client never re-promoted")

        # baseline through the proxy: bit-identical
        assert [view(o) for o in check_ok()] == want

        # partition hostB <-> svc: degrade FAST (idle timeout, not the
        # 600s request ceiling), cooldown armed, then heal + re-promote
        plane.partition_pairs({frozenset(("hostB", "svc"))})
        t0 = time.monotonic()
        assert client.check(packs) is None
        assert time.monotonic() - t0 < 30.0, "degradation took too long"
        assert client.broken
        plane.heal_partition()
        assert [view(o) for o in check_ok()] == want
        assert not client.broken

        # latency + jitter and slow-close: slow but correct
        plane.set_latency(30, 10)
        plane.set_slow_close(0.2)
        assert [view(o) for o in check_ok()] == want
        plane.heal()

        # fully lossy link: degrade; clear: recover
        plane.set_drop_prob(1.0)
        assert client.check(packs) is None
        plane.clear_drop_prob()
        assert [view(o) for o in check_ok()] == want
    finally:
        telemetry.set_current(prev if prev is not telemetry.NULL
                              else None)
        if client is not None:
            client.close()
        plane.close()
        svc.close()
        svc_mod.reset_clients()
    # the client reconnected (counted) rather than latching broken
    cctr = (tel.summary().get("counters") or {})
    assert cctr.get("service.reconnects", 0) >= 1, cctr
    # every successful check's packs attributed to hostB's ledger row
    sctr = (svc.stats().get("counters") or {})
    assert sctr.get("service.host_submitted.hostB") \
        == sctr.get("service.submitted"), sctr


def test_degraded_start_heals_mid_campaign(tmp_path, monkeypatch):
    """Satellite: a campaign that starts with its configured service
    DOWN checks in-process (graceful), then re-promotes mid-campaign
    once the service comes up — later runs ship packs, the ledger
    balances, and every verdict is bit-identical to a re-check."""
    monkeypatch.setattr(svc_mod, "RETRY_BASE_S", 0.02)
    monkeypatch.setattr(svc_mod, "RETRY_CAP_S", 0.05)
    svc_mod.reset_clients()
    path = str(tmp_path / "late-svc.sock")
    base = {"time_limit": 1, "rate": 100.0, "force_kernel": True,
            "nodes": ["n1", "n2", "n3"],
            "checker_service": path}  # configured, not yet listening
    # seed0=100: the coalescing test verified seeds 100.. all land
    # >=1 ok op per f (a zero-op seed honestly reports "unknown",
    # which would fail the expected-pass contract for other reasons)
    specs = campaign_specs(base, ["register"], [[]],
                           runs_per_cell=6, seed0=100)
    state = {"svc": None}
    lock = threading.Lock()

    def heal_after_two(row):
        with lock:
            if state["svc"] is None and row["index"] >= 1:
                state["svc"] = svc_mod.CheckerService(
                    path=path, tick_s=0.01).start()

    try:
        summary = run_campaign(specs, pool=0, service=False,
                               store_base=str(tmp_path), name="heal",
                               on_row=heal_after_two)
    finally:
        if state["svc"] is not None:
            state["svc"].close()
        svc_mod.reset_clients()
    assert state["svc"] is not None, "service never started"
    assert summary["valid?"] is True, summary["failures"]
    rows = summary["runs"]
    assert len(rows) == 6
    # phase 1 (service down): graceful in-process fallback, no errors
    assert rows[0]["service_shipped"] == 0
    assert rows[0]["service_fallbacks"] >= 1
    # phase 2 (service up): the negative cache EXPIRED — later runs
    # ship packs again instead of latching local forever
    assert any(r["service_shipped"] > 0 for r in rows[2:]), rows
    # producer-side ledger balances against what the late service saw
    svc_ctr = (state["svc"].stats().get("counters") or {})
    assert sum(r["service_shipped"] for r in rows) \
        == svc_ctr.get("service.submitted", 0), (rows, svc_ctr)
    for r in rows:
        stored = json.load(
            open(os.path.join(r["dir"], "results.json")))
        got = {str(k): {f: (v.get("linear") or {}).get(f)
                        for f in PROJECTION}
               for k, v in stored["workload"]["results"].items()}
        assert got == _recheck_locally(r["dir"]), r["dir"]


# -- agent pool unit-level robustness ----------------------------------------

def _fake_agent(endpoint, host, token, died):
    """Hand-rolled worker agent that speaks the registration protocol,
    accepts exactly ONE run frame, then dies mid-run (no row)."""
    sock = transport.connect(endpoint, timeout=5.0)
    try:
        transport.send_preamble(sock, host)
        transport.send_frame(sock, json.dumps(
            {"op": "register", "host": host, "token": token}).encode())
        reader = transport.FrameReader(sock)
        ok = json.loads(reader.recv_frame())
        assert ok.get("ok"), ok
        frame = reader.recv_frame()  # the run spec arrives...
        assert json.loads(frame).get("op") == "run"
    finally:
        sock.close()  # ...and the agent drops dead mid-run
        died.set()


def test_agent_death_requeues_then_runs_inline(tmp_path):
    """An agent dying mid-run re-queues the spec; with no surviving
    agents the driver runs it inline — the campaign still completes,
    and the requeue is on the ledger."""
    tel = Telemetry()
    pool = HostAgentPool(token="tok", tel=tel, idle_timeout=2.0).start()
    died = threading.Event()
    t = threading.Thread(target=_fake_agent,
                         args=(pool.endpoint, "flaky", "tok", died))
    t.start()
    try:
        assert pool.wait_ready(1, timeout=10.0) == 1
        assert pool.hosts() == ["flaky"]
        spec = {"index": 0,
                "opts": {"workload": "register", "time_limit": 1,
                         "rate": 100.0, "seed": 5,
                         "nodes": ["n1", "n2", "n3"],
                         "store_base": str(tmp_path)}}
        rows = []
        pool.run([spec], rows.append)
        t.join(timeout=10.0)
        assert died.is_set()
        assert pool.requeues >= 1
        assert len(rows) == 1, "stranded spec never completed"
        assert rows[0]["status"] == "done" and rows[0]["valid"] is True
        ctr = (tel.summary().get("counters") or {})
        assert ctr.get("campaign.agent_requeues", 0) >= 1, ctr
    finally:
        pool.close()


def test_agent_pool_zero_agents_runs_inline(tmp_path):
    """A fleet of zero registered agents degrades to the serial
    baseline: every spec runs inline in the driver."""
    pool = HostAgentPool().start()
    try:
        spec = {"index": 0,
                "opts": {"workload": "register", "time_limit": 1,
                         "rate": 100.0, "seed": 9,
                         "nodes": ["n1", "n2", "n3"],
                         "store_base": str(tmp_path)}}
        rows = []
        pool.run([spec], rows.append)
        assert len(rows) == 1
        assert rows[0]["status"] == "done"
    finally:
        pool.close()


def test_agent_pool_rejects_bad_token():
    """An agent with the wrong shared secret never joins the fleet."""
    pool = HostAgentPool(token="right").start()
    try:
        sock = transport.connect(pool.endpoint, timeout=5.0)
        try:
            transport.send_preamble(sock, "evil")
            transport.send_frame(sock, json.dumps(
                {"op": "register", "host": "evil",
                 "token": "wrong"}).encode())
            reader = transport.FrameReader(sock)
            sock.settimeout(5.0)
            resp = json.loads(reader.recv_frame())
            assert resp.get("error"), resp
        finally:
            sock.close()
        assert pool.wait_ready(1, timeout=0.5) == 0
        assert pool.hosts() == []
    finally:
        pool.close()


# -- multi-process TCP soak (slow tier) --------------------------------------

@pytest.mark.slow
def test_three_host_soak(tmp_path):
    """Larger fleet soak: 3 worker-agent processes, 24 runs, cross-host
    ledger balanced and every verdict bit-identical."""
    base = {"time_limit": 1, "rate": 100.0, "force_kernel": True,
            "nodes": ["n1", "n2", "n3"]}
    specs = campaign_specs(base, ["register"], [[], ["kill"]],
                           runs_per_cell=12, seed0=600)
    hosts = ["hostA", "hostB", "hostC"]
    summary = run_campaign(specs, pool=0, service=True,
                           service_tick_s=0.05, hosts=hosts,
                           store_base=str(tmp_path), name="soak")
    assert summary["valid?"] is True, summary["failures"]
    rows = summary["runs"]
    assert len(rows) == 24
    assert all(r["status"] == "done" for r in rows)
    _assert_cross_host_ledger(summary, hosts)
    for r in rows:
        stored = json.load(
            open(os.path.join(r["dir"], "results.json")))
        got = {str(k): {f: (v.get("linear") or {}).get(f)
                        for f in PROJECTION}
               for k, v in stored["workload"]["results"].items()}
        assert got == _recheck_locally(r["dir"]), r["dir"]
