"""Checker unit tests: the CPU linearizability oracle on known-good and
known-bad histories (SURVEY §4: golden histories regression-test checkers)."""

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.core.history import History
from jepsen_etcd_tpu.checkers import check_history
from jepsen_etcd_tpu.models import VersionedRegister, CASRegister, Mutex


def H(*ops):
    return History([Op(o) for o in ops])


def inv(p, f, v):
    return {"type": "invoke", "process": p, "f": f, "value": v}


def ok(p, f, v):
    return {"type": "ok", "process": p, "f": f, "value": v}


def info(p, f, v):
    return {"type": "info", "process": p, "f": f, "value": v}


def fail(p, f, v):
    return {"type": "fail", "process": p, "f": f, "value": v}


def test_trivial_valid():
    h = H(inv(0, "write", [None, 3]), ok(0, "write", [1, 3]),
          inv(0, "read", [None, None]), ok(0, "read", [1, 3]))
    assert check_history(VersionedRegister(), h)["valid?"] is True


def test_stale_read_invalid():
    h = H(inv(0, "write", [None, 3]), ok(0, "write", [1, 3]),
          inv(0, "write", [None, 4]), ok(0, "write", [2, 4]),
          inv(0, "read", [None, None]), ok(0, "read", [1, 3]))
    r = check_history(VersionedRegister(), h)
    assert r["valid?"] is False


def test_concurrent_reads_both_orders_valid():
    # two concurrent writes; a read may see either
    h = H(inv(0, "write", [None, 1]), inv(1, "write", [None, 2]),
          ok(1, "write", [None, 2]), ok(0, "write", [None, 1]),
          inv(2, "read", [None, None]), ok(2, "read", [2, 1]))
    assert check_history(VersionedRegister(), h)["valid?"] is True


def test_info_op_may_or_may_not_happen():
    # an indefinite write that a later read observes -> must have happened
    h = H(inv(0, "write", [None, 9]), info(0, "write", [None, 9]),
          inv(1, "read", [None, None]), ok(1, "read", [1, 9]))
    assert check_history(VersionedRegister(), h)["valid?"] is True
    # ...or is never observed -> also fine
    h2 = H(inv(0, "write", [None, 9]), info(0, "write", [None, 9]),
           inv(1, "read", [None, None]), ok(1, "read", [0, None]))
    assert check_history(VersionedRegister(), h2)["valid?"] is True


def test_failed_op_must_not_happen():
    h = H(inv(0, "write", [None, 9]), fail(0, "write", [None, 9]),
          inv(1, "read", [None, None]), ok(1, "read", [1, 9]))
    assert check_history(VersionedRegister(), h)["valid?"] is False


def test_cas_semantics():
    h = H(inv(0, "write", [None, 1]), ok(0, "write", [1, 1]),
          inv(0, "cas", [None, [1, 5]]), ok(0, "cas", [2, [1, 5]]),
          inv(0, "read", [None, None]), ok(0, "read", [2, 5]))
    assert check_history(VersionedRegister(), h)["valid?"] is True
    h2 = H(inv(0, "write", [None, 1]), ok(0, "write", [1, 1]),
           inv(0, "cas", [None, [2, 5]]), ok(0, "cas", [2, [2, 5]]))
    assert check_history(VersionedRegister(), h2)["valid?"] is False


def test_mutex_model():
    h = H(inv(0, "acquire", None), ok(0, "acquire", None),
          inv(1, "acquire", None), ok(1, "acquire", None))
    assert check_history(Mutex(), h)["valid?"] is False
    h2 = H(inv(0, "acquire", None), ok(0, "acquire", None),
           inv(0, "release", None), ok(0, "release", None),
           inv(1, "acquire", None), ok(1, "acquire", None))
    assert check_history(Mutex(), h2)["valid?"] is True


def test_cas_register_interleaving():
    # classic: read must not see a value after it was overwritten,
    # unless concurrent
    h = H(inv(0, "write", 1), ok(0, "write", 1),
          inv(1, "read", None), inv(2, "write", 2),
          ok(2, "write", 2), ok(1, "read", 2))
    assert check_history(CASRegister(), h)["valid?"] is True
