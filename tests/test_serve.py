"""HTTP smoke tests for serve.py over a hand-built fixture store:
index badges and artifact links, the per-run report page (parameters,
checkers, telemetry), the /aggregate cross-run dashboard (pass/fail
matrix, phase bars, failure dedupe), the ?trace event viewer, and
HTML escaping of run-controlled strings."""

import json
import threading
import urllib.request

import pytest

from jepsen_etcd_tpu.serve import make_server


def mk_run(base, test_name, run_name, results, test, history="",
           trace=None):
    d = base / test_name / run_name
    d.mkdir(parents=True)
    # all_runs only lists dirs that hold a history.jsonl
    (d / "history.jsonl").write_text(history)
    (d / "results.json").write_text(json.dumps(results))
    (d / "test.json").write_text(json.dumps(test))
    if trace is not None:
        (d / "trace.jsonl").write_text(
            "".join(json.dumps(e) + "\n" for e in trace))
    return d


TELEMETRY = {
    "schema": 1,
    "spans": {"phase:check": {"count": 1, "total_s": 0.5},
              "checker:workload": {"count": 1, "total_s": 0.4},
              "wgl.check_packed": {"count": 3, "total_s": 0.3}},
    "counters": {"engine.jnp-ladder": 3, "wgl.rungs": 7},
    "phases": {"setup": 0.1, "generate": 1.2, "teardown": 0.05,
               "check": 0.5},
    "checkers": {"workload": 0.4},
    "file": "telemetry.jsonl",
}


@pytest.fixture
def store(tmp_path):
    base = tmp_path / "store"
    mk_run(base, "etcd-register", "00001",
           {"valid?": True, "stats": {"valid?": True, "count": 120},
            "workload": {"valid?": True},
            "telemetry": TELEMETRY,
            "net-trace": {"events": 2, "dropped": 0,
                          "counts": {"send": 1, "deliver": 1}}},
           {"name": "etcd-register", "workload": "register",
            "nemesis_spec": [], "db_mode": "sim", "time_limit": 30,
            "rate": 200.0, "nodes": ["n1", "n2"]},
           trace=[{"t": 1_000_000, "kind": "send", "src": "n1",
                   "dst": "n2", "msg": "append"},
                  {"t": 2_000_000, "kind": "deliver", "src": "n2",
                   "dst": "n1", "msg": "append"},
                  {"truncated": 0}])
    mk_run(base, "etcd-register-kill", "00001",
           {"valid?": False, "stats": {"valid?": True, "count": 80},
            "workload": {"valid?": False}},
           {"name": "etcd-register-kill", "workload": "register",
            "nemesis_spec": ["kill"], "db_mode": "sim"})
    mk_run(base, "etcd-set-kill", "00001",
           {"valid?": False, "stats": {"valid?": True, "count": 60},
            "workload": {"valid?": False}},
           {"name": "etcd-set-kill", "workload": "set",
            "nemesis_spec": ["kill"], "db_mode": "sim"})
    mk_run(base, "weird", "00001",
           {"valid?": True, "stats": {"valid?": True, "count": 1}},
           {"name": "x<b>run</b>", "workload": "none",
            "nemesis_spec": [], "db_mode": "sim"})
    return base


@pytest.fixture
def server(store):
    srv = make_server(str(store), port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        srv.server_close()


def get(url):
    return urllib.request.urlopen(url, timeout=10).read().decode()


def test_index(server):
    page = get(server + "/")
    assert 'class="ok">True' in page
    assert 'class="bad">False' in page
    assert 'href="/aggregate"' in page
    assert "etcd-register/00001" in page
    assert "<td>120</td>" in page      # op count column


def test_run_page(server):
    page = get(server + "/etcd-register/00001/")
    assert "Parameters" in page and "Checkers" in page
    # telemetry section: phase bar, span table, counters, file link
    assert "Telemetry" in page
    assert "class='barbox'" in page
    assert "wgl.check_packed" in page and "<td>3</td>" in page
    assert "engine.jnp-ladder</code>=3" in page
    # net-trace summary links to the event viewer
    assert "Network trace" in page and "2 events" in page
    assert "?trace" in page
    # artifact links
    assert "results.json" in page and "history.jsonl" in page


def test_aggregate_dashboard(server):
    page = get(server + "/aggregate")
    assert "Cross-run dashboard" in page and "4 runs" in page
    # matrix: workload rows x (nemesis, db) columns with counts
    assert "Pass/fail matrix" in page
    assert "<th>register</th>" in page and "<th>set</th>" in page
    assert "kill" in page
    assert "1&nbsp;pass" in page and "1&nbsp;fail" in page
    # phase breakdown bars from telemetry (and the no-telemetry dim)
    assert "Phase breakdown" in page
    assert "class='barbox'" in page
    assert "no telemetry" in page
    # failure dedupe: both kill runs share one verdict signature
    assert "Failure dedupe" in page
    assert "workload=False" in page
    assert "<td>2</td>" in page


def test_trace_viewer(server):
    page = get(server + "/etcd-register/00001/?trace")
    assert "2 of 2 events shown" in page
    assert "<td>send</td>" in page and "<td>deliver</td>" in page
    assert "n1" in page and "append" in page
    # per-kind filter
    page = get(server + "/etcd-register/00001/?trace=send")
    assert "1 of 2 events shown" in page
    assert "<td>send</td>" in page and "<td>deliver</td>" not in page
    # a run without trace.jsonl degrades gracefully
    page = get(server + "/weird/00001/?trace")
    assert "no trace.jsonl" in page


def test_escaping(server):
    # run-controlled strings (test name) must never reach the page raw
    page = get(server + "/weird/00001/")
    assert "<b>run</b>" not in page
    assert "x&lt;b&gt;run&lt;/b&gt;" in page


def test_raw_files_still_served(server):
    raw = get(server + "/etcd-register/00001/results.json")
    assert json.loads(raw)["valid?"] is True
    listing = get(server + "/etcd-register/00001/?files")
    assert "Directory listing" in listing
    assert "history.jsonl" in listing
