"""Live fleet telemetry plane: log2 histograms, cross-process trace
propagation, the campaign live collector + /live SSE endpoint, and the
``tel`` mining CLI.

The plane's contract is accounting that JOINS across processes: every
record a run emits carries its campaign-minted trace id, service tick
spans list the run traces they coalesced, per-request queue waits
re-sum to the service's total, and every reader tolerates the torn
trailing line a killed writer leaves behind.
"""

import glob
import json
import os
import socket
import threading
import time
import urllib.request

import pytest

from jepsen_etcd_tpu import tel_cli
from jepsen_etcd_tpu.runner import checker_service as svc_mod
from jepsen_etcd_tpu.runner import telemetry
from jepsen_etcd_tpu.runner.campaign import LiveCollector, run_campaign
from jepsen_etcd_tpu.runner.telemetry import (HIST_MIN, SPAN_FIELDS,
                                              Hist, Telemetry,
                                              load_jsonl)
from jepsen_etcd_tpu.serve import make_server


@pytest.fixture(autouse=True)
def _isolate_current():
    telemetry.set_current(None)
    telemetry.set_thread_current(None)
    yield
    telemetry.set_current(None)
    telemetry.set_thread_current(None)


# -- histograms --------------------------------------------------------------

def test_hist_bucket_edges():
    assert Hist.bucket_of(0.0) == 0
    assert Hist.bucket_of(-5.0) == 0
    assert Hist.bucket_of(HIST_MIN) == 0
    assert Hist.bucket_of(HIST_MIN * 2) == 1
    assert Hist.bucket_of(HIST_MIN * 2.0001) == 2
    assert Hist.bucket_of(1e99) == 63
    assert Hist.bucket_edges(0) == (0.0, HIST_MIN)
    # upper edge is inclusive, lower exclusive: edges invert bucket_of
    for i in range(1, 63):
        lo, hi = Hist.bucket_edges(i)
        assert Hist.bucket_of(hi) == i
        assert Hist.bucket_of(lo) == i - 1


def test_hist_record_many_matches_scalar_path():
    vals = [0.0, HIST_MIN, 3e-6, 0.01, 2.5, 0.01]
    a, b = Hist(), Hist()
    for v in vals:
        a.record(v)
    b.record_many(vals)
    assert a.counts == b.counts
    assert (a.count, a.min, a.max) == (b.count, b.min, b.max)
    assert a.sum == pytest.approx(b.sum)


def test_hist_merge_is_bucketwise_addition():
    a, b = Hist(), Hist()
    a.record_many([1e-4, 2e-4, 5e-3])
    b.record_many([1e-4, 9.0])
    merged = Hist.from_dict(a.to_dict()).merge(Hist.from_dict(
        b.to_dict()))
    assert merged.count == 5
    assert merged.sum == pytest.approx(a.sum + b.sum)
    assert merged.min == pytest.approx(1e-4)
    assert merged.max == pytest.approx(9.0)
    both = Hist()
    both.record_many([1e-4, 2e-4, 5e-3, 1e-4, 9.0])
    assert merged.counts == both.counts


def test_hist_percentile_interpolates_and_clamps():
    h = Hist()
    for _ in range(4):
        h.record(0.004)
    # single observed value: every percentile clamps to it exactly
    for q in (1, 50, 95, 99, 100):
        assert h.percentile(q) == 0.004
    h2 = Hist()
    h2.record(0.0015)        # bucket 11: (1.024ms, 2.048ms]
    for _ in range(3):
        h2.record(0.004)     # bucket 12: (2.048ms, 4.096ms]
    p50 = h2.percentile(50)
    assert 0.002 < p50 < 0.003  # interpolated inside bucket 12
    d = h2.to_dict()
    assert d["buckets"] == {"11": 1, "12": 3}
    assert d["count"] == 4


def test_hist_empty_rendering():
    h = Hist()
    assert h.percentile(99) is None
    d = h.to_dict()
    assert d == {"count": 0, "sum": 0.0, "min": None, "max": None,
                 "p50": None, "p95": None, "p99": None, "buckets": {}}
    r = Hist.from_dict(d)
    assert r.count == 0 and r.to_dict() == d


# -- trace propagation -------------------------------------------------------

def test_trace_fields_ride_after_pinned_schema(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tel = Telemetry(path, trace="camp.r1", parent="camp")
    with tel.span("phase:generate"):
        pass
    tel.counter("wgl.rungs", 2)
    tel.hist("service.queue_wait_s", 0.002)
    tel.close()
    recs, skipped = load_jsonl(path)
    assert skipped == 0 and recs
    for r in recs:
        assert r["trace"] == "camp.r1"
        assert r["parent"] == "camp"
    span = next(r for r in recs if r["kind"] == "span")
    # pinned fields first, trace identity appended after
    assert tuple(span.keys()) == SPAN_FIELDS + ("trace", "parent")
    hist_rec = next(r for r in recs if r["kind"] == "hist")
    assert hist_rec["name"] == "service.queue_wait_s"
    assert hist_rec["count"] == 1 and hist_rec["buckets"]
    assert tel.summary()["trace"] == "camp.r1"


def test_traceless_recorder_keeps_exact_pinned_keys(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tel = Telemetry(path)
    with tel.span("phase:check"):
        pass
    tel.close()
    recs, _ = load_jsonl(path)
    span = next(r for r in recs if r["kind"] == "span")
    assert tuple(span.keys()) == SPAN_FIELDS
    assert "trace" not in tel.summary()


def test_load_jsonl_tolerates_torn_and_junk_lines(tmp_path):
    path = tmp_path / "t.jsonl"
    path.write_bytes(
        b'{"kind":"event","name":"a","t":0,"attrs":{}}\n'
        b"[1, 2]\n"                       # decodes, not a dict
        b"\xff\xfenot json at all\n"      # undecodable garbage
        b'{"kind":"span","name":"phase:gen","t0":1,"t')  # torn tail
    recs, skipped = load_jsonl(str(path))
    assert len(recs) == 1 and recs[0]["name"] == "a"
    assert skipped == 3
    # a missing file is empty, never an exception
    assert load_jsonl(str(tmp_path / "nope.jsonl")) == ([], 0)


def test_thread_local_override_does_not_leak_across_threads(tmp_path):
    """Pins the checker-service fix: a worker thread pinning its own
    recorder via set_thread_current must never redirect other
    threads' telemetry.current() (the old global set_current swap
    did, losing main-thread records into the service stream)."""
    a = Telemetry(str(tmp_path / "a.jsonl"))
    b = Telemetry(str(tmp_path / "b.jsonl"), trace="svc")
    telemetry.set_current(a)
    errs = []
    started = threading.Event()

    def worker():
        telemetry.set_thread_current(b)
        started.set()
        try:
            for _ in range(300):
                if telemetry.current() is not b:
                    errs.append("worker lost its override")
                    return
                telemetry.current().counter("service.ticks")
        finally:
            telemetry.set_thread_current(None)

    t = threading.Thread(target=worker)
    t.start()
    started.wait(5)
    for _ in range(300):
        if telemetry.current() is not a:
            errs.append("main thread redirected")
            break
        telemetry.current().counter("campaign.runs")
    t.join(10)
    assert not errs
    a.close()
    b.close()
    assert a.summary()["counters"].get("campaign.runs") == 300
    assert "service.ticks" not in a.summary()["counters"]
    assert b.summary()["counters"].get("service.ticks") == 300


# -- service: tick spans + queue-wait attribution ----------------------------

def test_service_ticks_list_run_traces_and_waits_resum(tmp_path):
    from test_checker_service import make_packs
    svc_log = str(tmp_path / "service.jsonl")
    svc_tel = Telemetry(svc_log, trace="c.svc", parent="c")
    svc = svc_mod.CheckerService(tick_s=0.01, tel=svc_tel).start()
    try:
        c1 = svc_mod.CheckerClient(svc.path)
        c2 = svc_mod.CheckerClient(svc.path)
        packs = make_packs(5, 3)
        assert c1.last_queue_wait_s is None
        out1 = c1.check(packs[:2], trace="c.r0")
        out2 = c2.check(packs[2:], trace="c.r1")
        assert out1 is not None and out2 is not None
        waits = [c1.last_queue_wait_s, c2.last_queue_wait_s]
        assert all(isinstance(w, float) and w >= 0 for w in waits)
        ctr = svc.stats().get("counters") or {}
        # per-request attribution re-sums to the service's own total
        assert sum(waits) == pytest.approx(
            ctr.get("service.queue_wait_s"), abs=1e-4)
        assert any(k.startswith("service.device_busy_s.")
                   for k in ctr), sorted(ctr)
        c1.close()
        c2.close()
    finally:
        svc.close()
        svc_mod.reset_clients()
    svc_tel.close()
    recs, skipped = load_jsonl(svc_log)
    assert skipped == 0
    ticks = [r for r in recs if r.get("kind") == "span"
             and r.get("name") == "service.tick"]
    assert ticks
    listed = set()
    for tk in ticks:
        assert tk["trace"] == "c.svc" and tk["parent"] == "c"
        attrs = tk.get("attrs") or {}
        assert attrs.get("device")
        listed.update(attrs.get("runs") or [])
    assert {"c.r0", "c.r1"} <= listed
    hist_names = {r["name"] for r in recs if r.get("kind") == "hist"}
    assert {"service.queue_wait_s", "service.tick"} <= hist_names


# -- live collector ----------------------------------------------------------

def _wait_until(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def test_live_collector_folds_worker_stream(tmp_path):
    col = LiveCollector(str(tmp_path), trace="camp").start()
    try:
        tel = Telemetry(str(tmp_path / "r0.jsonl"), trace="camp.r0",
                        parent="camp", sink=col.path)
        with tel.span("phase:generate"):
            pass
        tel.counter("net.dropped_chunks", 3)
        tel.hist("op.latency.write", 0.004)
        tel.close()  # flushes the counter + hist records to the sink
        assert tel.sink_dropped == 0
        assert _wait_until(lambda: col.records >= 3)
        # junk datagram: counted as bad, never kills the collector
        s = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        s.sendto(b"not json", col.path)
        s.close()
        assert _wait_until(lambda: col.bad == 1)
        col.note_row({"trace": "camp.r0", "index": 0,
                      "status": "done", "valid": True})
    finally:
        stats = col.close()
    assert stats["records"] >= 3
    assert stats["bad"] == 1 and stats["dropped"] == 0
    assert not os.path.exists(col.path), "socket not unlinked"
    snap = json.load(open(os.path.join(str(tmp_path), "live.json")))
    assert snap["done"] is True and snap["campaign"] == "camp"
    st = snap["runs"]["camp.r0"]
    assert st["status"] == "done" and st["valid"] is True
    assert st["spans"] >= 1
    assert snap["counters"].get("net.dropped_chunks") == 3
    assert snap["hists"]["op.latency.*"]["count"] == 1


def test_sink_to_dead_socket_never_fails_the_run(tmp_path):
    tel = Telemetry(str(tmp_path / "t.jsonl"), trace="x",
                    sink=str(tmp_path / "no-collector.sock"))
    for i in range(10):
        with tel.span("phase:generate"):
            pass
    tel.close()
    recs, skipped = load_jsonl(str(tmp_path / "t.jsonl"))
    assert skipped == 0 and len(recs) == 10
    assert tel.sink_dropped >= 1


# -- /live SSE ---------------------------------------------------------------

@pytest.fixture
def http_store(tmp_path):
    srv = make_server(str(tmp_path), port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", tmp_path
    finally:
        srv.shutdown()
        srv.server_close()


def test_live_page_and_inactive_sse(http_store):
    url, _ = http_store
    page = urllib.request.urlopen(url + "/live",
                                  timeout=10).read().decode()
    assert "EventSource" in page and "sse=1" in page
    # no campaign ever ran live: exactly one terminal event
    body = urllib.request.urlopen(url + "/live?sse=1",
                                  timeout=10).read().decode()
    assert body.startswith("data: ")
    assert json.loads(body[len("data: "):].strip()) == \
        {"active": False}


def test_live_sse_streams_fresh_snapshot(http_store):
    url, base = http_store
    cdir = base / "camp" / "00000"
    cdir.mkdir(parents=True)
    (cdir / "live.json").write_text(json.dumps({
        "campaign": "camp-00000", "records": 5, "dropped": 0,
        "bad": 0, "runs": {"camp-00000.r0": {"spans": 3,
                                             "phase": "generate"}},
        "service": {}, "counters": {}, "hists": {}, "done": False}))
    resp = urllib.request.urlopen(url + "/live?sse=1", timeout=10)
    line = resp.readline()
    while line and not line.startswith(b"data: "):
        line = resp.readline()
    resp.close()
    payload = json.loads(line[len(b"data: "):].decode())
    assert payload["active"] is True
    assert payload["campaign"] == "camp-00000"
    assert payload["runs"]["camp-00000.r0"]["phase"] == "generate"
    assert payload["dir"] == os.path.join("camp", "00000")


# -- campaign e2e: collector + SSE mid-campaign + mining ---------------------

def test_pool_campaign_live_plane_e2e(tmp_path):
    """3 sim runs over a 2-worker pool with the live plane on: /live
    serves an SSE update while the campaign is still running, the
    collector's fold survives to campaign.json (trace ids, p50/95/99
    triples, net counters), and the tel CLI's ledger + coverage both
    verify the artifacts."""
    specs = [{"index": i,
              "opts": {"workload": "register", "time_limit": 1,
                       "rate": 100.0, "seed": 11 + i,
                       "nodes": ["n1", "n2", "n3"]}}
             for i in range(3)]
    res = {}

    def go():
        try:
            res["summary"] = run_campaign(
                specs, pool=2, service=False,
                store_base=str(tmp_path), name="livecamp")
        except BaseException as e:  # surfaced by the main thread
            res["err"] = e

    t = threading.Thread(target=go, daemon=True)
    t.start()
    live = None
    deadline = time.time() + 120
    while time.time() < deadline and not live:
        found = glob.glob(os.path.join(str(tmp_path), "livecamp",
                                       "*", "live.json"))
        live = found[0] if found else None
        time.sleep(0.1)
    assert live, "collector never published live.json"

    srv = make_server(str(tmp_path), port=0)
    st = threading.Thread(target=srv.serve_forever, daemon=True)
    st.start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        resp = urllib.request.urlopen(url + "/live?sse=1", timeout=30)
        line = resp.readline()
        while line and not line.startswith(b"data: "):
            line = resp.readline()
        resp.close()
        payload = json.loads(line[len(b"data: "):].decode())
        assert "active" in payload and "runs" in payload
        assert payload["campaign"].startswith("livecamp-")
    finally:
        srv.shutdown()
        srv.server_close()

    t.join(timeout=600)
    assert not t.is_alive(), "campaign hung"
    assert "err" not in res, res.get("err")
    summary = res["summary"]
    assert summary["valid?"] is True
    ctr = summary["telemetry"]["counters"]
    assert ctr.get("live.records", 0) > 0
    assert ctr.get("live.dropped", 0) == 0
    for r in summary["runs"]:
        assert r["trace"] == f"{summary['trace']}.r{r['index']}"
        assert set(r["net"]) == {"dropped_chunks", "accept_errors",
                                 "delayed_bytes"}
        assert len(r["p"]["gen"]) == 3  # [p50, p95, p99]
        assert r["hists"]["gen"]["count"] > 0
    assert len(summary["p"]["gen"]) == 3
    snap = json.load(open(live))
    assert snap["done"] is True
    assert set(snap["runs"]) >= {r["trace"] for r in summary["runs"]}

    led = tel_cli.ledger(summary["dir"])
    assert led["ok"] is True, led
    cov = tel_cli.coverage(summary["dir"])
    assert cov["aggregate"]["count"] == 3
    assert cov["aggregate"]["invalid"] == 0


# -- tel CLI -----------------------------------------------------------------

def _mini_run(path, trace=None, lat=0.01):
    tel = Telemetry(str(path), trace=trace)
    with tel.span("phase:check"):
        pass
    tel.hist("service.queue_wait_s", lat)
    tel.close()


def test_tel_cli_spans_over_dir(tmp_path, capsys):
    _mini_run(tmp_path / "telemetry.jsonl", trace="t1")
    rc = tel_cli.cmd_spans([str(tmp_path)], as_json=True)
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["traces"] == ["t1"]
    assert "phase:check" in out["spans"]
    assert out["hists"]["service.queue_wait_s"]["count"] == 1
    assert out["skipped"] == 0
    # torn trailing line: counted, never fatal
    with open(tmp_path / "telemetry.jsonl", "ab") as f:
        f.write(b'{"kind":"span","na')
    rc = tel_cli.cmd_spans([str(tmp_path)], as_json=True)
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["skipped"] == 1


def test_tel_cli_diff(tmp_path, capsys):
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    _mini_run(a / "telemetry.jsonl")
    _mini_run(b / "telemetry.jsonl")
    rc = tel_cli.cmd_diff([str(a), str(b)], as_json=True)
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    d = next(s for s in out["spans"] if s["span"] == "phase:check")
    assert d["count_a"] == 1 and d["count_b"] == 1
    assert d["p95_ratio"] is not None
    with pytest.raises(SystemExit):
        tel_cli.cmd_diff([str(a)], as_json=True)


def test_tel_cli_ledger_flags_mismatches(tmp_path, capsys):
    (tmp_path / "campaign.json").write_text(json.dumps({
        "trace": "c", "runs": [
            {"status": "done", "trace": "c.r0", "service_shipped": 5,
             "service_queue_wait_s": 0.5}],
        "service": {"counters": {"service.submitted": 4,
                                 "service.queue_wait_s": 0.5}}}))
    # service.jsonl whose ticks never list c.r0: join must fail too
    with open(tmp_path / "service.jsonl", "w") as f:
        f.write(json.dumps({"kind": "span", "name": "service.tick",
                            "t0": 0, "t1": 1, "dur_s": 1,
                            "attrs": {"runs": ["c.r9"]}}) + "\n")
    rc = tel_cli.cmd_ledger([str(tmp_path)], as_json=True)
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["ok"] is False
    by = {c["check"]: c for c in out["checks"]}
    assert by["shipped==submitted"]["ok"] is False
    assert by["queue_wait attribution"]["ok"] is True
    assert by["trace join (rows ⊆ tick spans)"]["ok"] is False


def test_tel_cli_coverage_vector(tmp_path):
    rdir = tmp_path / "etcd-register" / "00001"
    rdir.mkdir(parents=True)
    (rdir / "results.json").write_text(json.dumps({
        "valid?": False, "workload": {"valid?": False},
        "telemetry": {"counters": {"wgl.max-frontier": 17,
                                   "wgl.rungs": 3,
                                   "wgl.host-spill": 1}}}))
    out = tel_cli.coverage(str(tmp_path))
    agg = out["aggregate"]
    assert agg["count"] == 1 and agg["peak_frontier"] == 17
    assert agg["rungs"] == 3 and agg["spills"] == 1
    assert agg["invalid"] == 1
    assert agg["signatures"] == {"workload=False": 1}
