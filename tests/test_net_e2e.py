"""Network-fault e2e through the userspace proxy plane (net/).

A 3-node fake-etcd cluster with every peer/client URL fronted by the
plane (--net-proxy): a partitioned minority refuses writes with the
wire shape real etcd gives (503 / "etcdserver: no leader" -> an
indefinite SimError), the majority keeps progressing, and healing
restores the minority — plus the nemesis partition/latency packages
driving the SAME plane through their local-mode backend. The
real-binary variant runs behind @pytest.mark.live like every other
real-etcd path (tests/test_live_etcd.py)."""

import time

import pytest

from jepsen_etcd_tpu.core.op import Op
from jepsen_etcd_tpu.db.local import LocalDb
from jepsen_etcd_tpu.nemesis.packages import nemesis_package
from jepsen_etcd_tpu.runner.sim import set_current_loop
from jepsen_etcd_tpu.runner.wall import WallLoop
from jepsen_etcd_tpu.sut.errors import SimError

NODES = ["n1", "n2", "n3"]

#: how a quorum-less node may classify a write: the fake answers 503
#: "etcdserver: no leader" immediately; a real minority hangs into the
#: client deadline
UNAVAILABLE = {"unavailable", "no-leader", "timeout"}

#: peer-visibility probes run every 0.25 s with 1 s reply deadlines
#: (db/fake_etcd.py), so convergence comfortably fits this window
CONVERGE_S = 12.0


@pytest.fixture()
def wall_loop():
    loop = WallLoop()
    set_current_loop(loop)
    yield loop
    set_current_loop(None)
    loop.shutdown()


def build_proxied(tmp_path, binary="fake", nodes=NODES):
    db = LocalDb({"etcd_binary": binary,
                  "etcd_data_dir": str(tmp_path / "data"),
                  "client_type": "http",
                  "nodes": list(nodes),
                  "net_proxy": True,
                  "seed": 11})
    test = {"nodes": list(nodes), "client_type": "http",
            "db_mode": "local", "db": db}
    return db, test


@pytest.fixture()
def proxied_cluster(wall_loop, tmp_path):
    db, test = build_proxied(tmp_path)
    wall_loop.run_coro(db.setup(test))
    try:
        yield wall_loop, db, test
    finally:
        db.stop_all()
        assert db.leaked_pids() == []


def try_put(loop, db, test, node, key, value):
    """One write; returns None on success or the classified SimError."""
    async def story():
        c = db._client(test, node)
        try:
            await c.put(key, value)
            return None
        except SimError as e:
            return e
        finally:
            c.close()
    return loop.run_coro(story())


def await_write_fails(loop, db, test, node, timeout=CONVERGE_S):
    """Poll until a write to ``node`` raises (probe convergence is
    asynchronous); returns the SimError."""
    deadline = time.monotonic() + timeout
    err = None
    while time.monotonic() < deadline:
        err = try_put(loop, db, test, node, "poll-fail", 0)
        if err is not None:
            return err
        time.sleep(0.25)
    raise AssertionError(f"writes to {node} never started failing")


def await_write_ok(loop, db, test, node, timeout=CONVERGE_S):
    deadline = time.monotonic() + timeout
    err = None
    while time.monotonic() < deadline:
        err = try_put(loop, db, test, node, "poll-ok", 0)
        if err is None:
            return
        time.sleep(0.25)
    raise AssertionError(f"writes to {node} still failing: {err}")


def node_status(loop, db, test, node):
    async def story():
        c = db._client(test, node)
        try:
            return await c.status()
        finally:
            c.close()
    return loop.run_coro(story())


# ---- the acceptance story ---------------------------------------------------

def test_partition_minority_fails_majority_progresses_heals(
        proxied_cluster):
    loop, db, test = proxied_cluster
    plane = db.plane
    assert plane is not None
    # every node's client AND peer URL is fronted
    assert plane.stats()["links"] == 2 * len(NODES)
    for node in NODES:
        assert db.client_url(node) != db.listen_client_url(node)
    # healthy: every node takes writes and reports a leader
    for i, node in enumerate(NODES):
        assert try_put(loop, db, test, node, "k-setup", i) is None
        assert node_status(loop, db, test, node)["leader"]

    plane.partition([["n1", "n2"], ["n3"]])
    # the minority loses its roster majority once probes converge:
    # writes refuse with the real-etcd wire shape, INDEFINITE (the op
    # may not have happened -> :info in a run, never :fail-definite)
    err = await_write_fails(loop, db, test, "n3")
    assert err.type in UNAVAILABLE, err
    assert err.definite is not True
    assert node_status(loop, db, test, "n3")["leader"] is None
    # the majority side keeps progressing throughout
    assert try_put(loop, db, test, "n1", "k-maj", 1) is None
    assert try_put(loop, db, test, "n2", "k-maj", 2) is None

    plane.heal_partition()
    await_write_ok(loop, db, test, "n3")
    assert node_status(loop, db, test, "n3")["leader"]


def test_one_way_drop_degrades_visibility(proxied_cluster):
    """An asymmetric drop (n3's INBOUND from everyone severed on the
    probe round trip) still costs n3 its quorum: visibility needs the
    round trip, not just one leg."""
    loop, db, test = proxied_cluster
    db.plane.partition_pairs({("n1", "n3"), ("n2", "n3")})
    err = await_write_fails(loop, db, test, "n3")
    assert err.type in UNAVAILABLE, err
    # n1 still sees n2 (and vice versa): majority intact
    assert try_put(loop, db, test, "n1", "k-ow", 1) is None
    db.plane.heal_partition()
    await_write_ok(loop, db, test, "n3")


# ---- nemesis packages drive the plane backend -------------------------------

def test_nemesis_partition_package_drives_plane(proxied_cluster):
    loop, db, test = proxied_cluster
    plane = db.plane
    nem = nemesis_package({"nemesis": ["partition"], "nodes": NODES,
                           "nemesis_interval": 1})
    n = nem["nemesis"]
    assert {"start-partition", "stop-partition"} <= n.fs

    op = loop.run_coro(n.invoke(test, Op(type="invoke",
                                         f="start-partition",
                                         value="majority")))
    assert op.type == "info"
    assert plane.stats()["blocked"] == 2  # 3 nodes: 1x2 cross pairs
    loop.run_coro(n.invoke(test, Op(type="invoke", f="stop-partition",
                                    value=None)))
    assert plane.stats()["blocked"] == 0

    # one-way spec installs ORDERED tuples (asymmetric blackhole)
    op = loop.run_coro(n.invoke(test, Op(type="invoke",
                                         f="start-partition",
                                         value="one-way")))
    assert "blocked links" in str(op.value)
    assert plane.blocked and all(
        isinstance(p, tuple) and not isinstance(p, frozenset)
        for p in plane.blocked)
    srcs = {p[0] for p in plane.blocked}
    assert len(srcs) == 1 and len(plane.blocked) == len(NODES) - 1
    loop.run_coro(n.invoke(test, Op(type="invoke", f="stop-partition",
                                    value=None)))
    assert plane.stats()["blocked"] == 0


def test_nemesis_latency_package_slows_the_wire(proxied_cluster):
    loop, db, test = proxied_cluster
    nem = nemesis_package({"nemesis": ["latency"], "nodes": NODES,
                           "nemesis_interval": 1})
    n = nem["nemesis"]
    assert {"start-latency", "stop-latency"} <= n.fs
    loop.run_coro(n.invoke(test, Op(type="invoke", f="start-latency",
                                    value={"delta-ms": 150,
                                           "jitter-ms": 10})))
    assert db.plane.latency is not None
    t0 = time.monotonic()
    assert try_put(loop, db, test, "n1", "k-slow", 1) is None
    # request + response each pay >= delta on the client leg
    assert time.monotonic() - t0 >= 0.15
    op = loop.run_coro(n.invoke(test, Op(type="invoke",
                                         f="stop-latency", value=None)))
    assert op.value == "latency-cleared"
    assert db.plane.latency is None
    assert try_put(loop, db, test, "n1", "k-fast", 2) is None


# ---- the real binary, gated like every live path ----------------------------

@pytest.mark.live
def test_real_etcd_partition_through_proxy(etcd_binary, wall_loop,
                                           tmp_path):
    """Same story against real etcd: member-id attribution (sniffed
    X-Server-From -> names registered post-setup) lets the plane cut
    raft links; a minority leader loses quorum, the majority elects
    around it, heal restores."""
    db, test = build_proxied(tmp_path, binary=[etcd_binary])
    wall_loop.run_coro(db.setup(test))
    try:
        plane = db.plane
        # attribution installed from member_list() after setup
        assert set(plane.member_names.values()) == set(NODES)
        await_write_ok(wall_loop, db, test, "n1")
        plane.partition([["n1", "n2"], ["n3"]])
        err = await_write_fails(wall_loop, db, test, "n3", timeout=30)
        assert err.type in UNAVAILABLE, err
        # the majority side elects within its own half and progresses
        await_write_ok(wall_loop, db, test, "n1", timeout=30)
        plane.heal_partition()
        await_write_ok(wall_loop, db, test, "n3", timeout=30)
    finally:
        db.stop_all()
        assert db.leaked_pids() == []
