"""bench.py --dry smoke mode under tier-1.

The dry path exercises the same code as each matrix cell at tiny sizes
and asserts STRUCTURE (engine routing, packer equivalence) — never
timings — so it is safe on any host with JAX_PLATFORMS=cpu. These tests
pin the CLI contract: one JSON line on stdout, per-cell {"ok": true}.

One all-cells ``bench.py --dry`` subprocess is shared by every
positive test (module-scoped fixture): the per-cell assertions are
unchanged, but the suite pays ONE interpreter + jax + lint-gate
startup instead of one per cell — and the all-cells run additionally
proves every registered dry check passes, not just the ones asserted
in detail below. The ``--cell`` selection contract keeps its own
tests (one positive single-cell run, one unknown-name rejection).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_dry(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench.py", "--dry", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.fixture(scope="module")
def dry_all():
    """One shared all-cells dry run; every registered cell must be
    present and ok before any per-cell structure is inspected."""
    res = run_dry()
    assert all(c.get("ok") is True for c in res["dry"].values()), \
        {k: c.get("ok") for k, c in res["dry"].items()}
    return res["dry"]


def test_dry_single_cell_selection():
    """--cell picks exactly one cell (the CLI contract the campaign
    and CI wrappers rely on)."""
    res = run_dry("--cell", "set_full")
    assert list(res["dry"]) == ["set_full"]
    cell = res["dry"]["set_full"]
    assert cell["ok"] is True and cell["check"] == "_dry_set"
    assert cell["attempts"] > 0


def test_dry_batched_cell(dry_all):
    cell = dry_all["batched_512_keys"]
    assert cell["ok"] is True
    assert cell["check"] == "_dry_batched"
    assert cell["mxu_supported"] >= 1
    assert cell["engines"] == ["cpu-oracle"]


def test_dry_set_cell(dry_all):
    cell = dry_all["set_full"]
    assert cell["ok"] is True and cell["check"] == "_dry_set"
    assert cell["attempts"] > 0


def test_dry_gen_throughput_cell(dry_all):
    """Tier-1 guard on the batched bench leg's structure: a 16-seed
    batch generates deterministically, born-columnar, with
    self-consistent genbatch stats (timings asserted only by the real
    bench run, never here)."""
    cell = dry_all["gen_throughput"]
    assert cell["ok"] is True and cell["check"] == "_dry_gen_throughput"
    assert cell["ops"] > 0 and cell["events"] > 0
    batched = cell["batched"]
    assert batched["seeds"] == 16
    assert batched["events"] > 0 and batched["steps"] > 0
    jitted = cell["jitted"]
    assert jitted["seeds"] == 16
    assert jitted["events"] > 0


def test_dry_fused_pipeline_cell(dry_all):
    """Tier-1 guard on the fused cell's structure: every seed gets a
    verdict, the verdict map matches the sequential twin (asserted
    inside the dry check itself), and pack/wave accounting is live —
    the e2e/max ratio is only measured by the real bench run."""
    cell = dry_all["fused_pipeline"]
    assert cell["ok"] is True and cell["check"] == "_dry_fused_pipeline"
    assert cell["seeds"] == 4
    assert cell["packs"] >= 4
    assert cell["waves"] > 0
    assert sorted(cell["verdicts"]) == ["0", "1", "2", "3"] or \
        sorted(cell["verdicts"]) == [0, 1, 2, 3]


def test_dry_streaming_cell(dry_all):
    cell = dry_all["streaming_overlap"]
    assert cell["ok"] is True and cell["check"] == "_dry_streaming"
    assert cell["chunks"] >= 2
    assert cell["ops"] > 0


def test_dry_net_overhead_cell(dry_all):
    """Tier-1 guard: a no-fault proxied local run's verdict skeleton
    is bit-identical to the direct run's (the proxy plane is invisible
    to checkers)."""
    cell = dry_all["net_overhead"]
    assert cell["ok"] is True and cell["check"] == "_dry_net_overhead"
    assert cell["links"] == 2
    assert cell["verdicts_identical"] is True


def test_dry_telemetry_overhead_cell(dry_all):
    """Tier-1 guard on the observability cell's structure: both arms
    run, the on-arm records into a traced recorder whose summary
    carries the op-latency histogram — the overhead percentage itself
    is never asserted."""
    cell = dry_all["telemetry_overhead"]
    assert cell["ok"] is True and cell["check"] == \
        "_dry_telemetry_overhead"
    assert cell["records"] > 0
    assert cell["hist_count"] > 0


def test_dry_campaign_cell(dry_all):
    cell = dry_all["campaign_amortization"]
    assert cell["ok"] is True and cell["check"] == "_dry_campaign"
    assert cell["packs"] == 2
    assert cell["verdicts_identical"] is True


def test_dry_service_scaling_cell(dry_all):
    """Tier-1 guard on the multi-device service cell's structure: the
    service's verdicts match local check_packed bit-for-bit, the
    per-device dispatch counters balance the group ledger, and — when
    the forced 8-device mesh is visible — distinct group shapes use
    distinct chips (the check-wall ratio itself is only reported by
    the real bench run, never asserted)."""
    cell = dry_all["service_scaling"]
    assert cell["ok"] is True and cell["check"] == \
        "_dry_service_scaling"
    assert cell["verdicts_identical"] is True
    assert cell["packs"] >= 2
    assert cell["devices"] >= 1
    assert cell["chips_used"] >= 1


def test_dry_guided_search_cell(dry_all):
    """Tier-1 guard on the guided-search cell's structure: same-seed
    schedulers emit identical candidate generations, and a drawn fault
    plan replays bit-identically as an explicit schedule (singly and as
    a batched population) — the runs-to-failure speedup itself is only
    measured by the real bench run, never here."""
    cell = dry_all["guided_search"]
    assert cell["ok"] is True and cell["check"] == "_dry_guided_search"
    assert cell["candidates"] == 18
    assert cell["mutated"] >= 1
    assert cell["windows"] >= 1
    assert cell["replay_identical"] is True


def test_dry_store_index_cell(dry_all):
    """Tier-1 guard on the indexed-store cell's structure: a rebuilt
    index replays the walk's rows exactly, survives the fingerprint
    verify, matches incremental writes row-for-row, and the /aggregate
    pager clamps — the 100-vs-10k latency ratio is only measured by
    the real bench run, never here."""
    cell = dry_all["store_index"]
    assert cell["ok"] is True and cell["check"] == "_dry_store_index"
    assert cell["runs"] == 12 and cell["rows"] == 12
    assert cell["fingerprint"]["tree"] == cell["fingerprint"]["index"]
    assert cell["incremental"] == 3


def test_dry_rejects_unknown_cell():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench.py", "--dry", "--cell", "nope"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
