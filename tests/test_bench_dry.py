"""bench.py --cell <name> --dry smoke mode under tier-1.

The dry path exercises the same code as each matrix cell at tiny sizes
and asserts STRUCTURE (engine routing, packer equivalence) — never
timings — so it is safe on any host with JAX_PLATFORMS=cpu. These tests
pin the CLI contract: one JSON line on stdout, per-cell {"ok": true}.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_dry(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench.py", "--dry", *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_dry_batched_cell():
    res = run_dry("--cell", "batched_512_keys")
    cell = res["dry"]["batched_512_keys"]
    assert cell["ok"] is True
    assert cell["check"] == "_dry_batched"
    assert cell["mxu_supported"] >= 1
    assert cell["engines"] == ["cpu-oracle"]


def test_dry_set_cell():
    res = run_dry("--cell", "set_full")
    cell = res["dry"]["set_full"]
    assert cell["ok"] is True and cell["check"] == "_dry_set"
    assert cell["attempts"] > 0


def test_dry_gen_throughput_cell():
    """Tier-1 guard on the batched bench leg's structure: a 16-seed
    batch generates deterministically, born-columnar, with
    self-consistent genbatch stats (timings asserted only by the real
    bench run, never here)."""
    res = run_dry("--cell", "gen_throughput")
    cell = res["dry"]["gen_throughput"]
    assert cell["ok"] is True and cell["check"] == "_dry_gen_throughput"
    assert cell["ops"] > 0 and cell["events"] > 0
    batched = cell["batched"]
    assert batched["seeds"] == 16
    assert batched["events"] > 0 and batched["steps"] > 0


def test_dry_streaming_cell():
    res = run_dry("--cell", "streaming_overlap")
    cell = res["dry"]["streaming_overlap"]
    assert cell["ok"] is True and cell["check"] == "_dry_streaming"
    assert cell["chunks"] >= 2
    assert cell["ops"] > 0


def test_dry_net_overhead_cell():
    """Tier-1 guard: a no-fault proxied local run's verdict skeleton
    is bit-identical to the direct run's (the proxy plane is invisible
    to checkers)."""
    res = run_dry("--cell", "net_overhead")
    cell = res["dry"]["net_overhead"]
    assert cell["ok"] is True and cell["check"] == "_dry_net_overhead"
    assert cell["links"] == 2
    assert cell["verdicts_identical"] is True


def test_dry_telemetry_overhead_cell():
    """Tier-1 guard on the observability cell's structure: both arms
    run, the on-arm records into a traced recorder whose summary
    carries the op-latency histogram — the overhead percentage itself
    is never asserted."""
    res = run_dry("--cell", "telemetry_overhead")
    cell = res["dry"]["telemetry_overhead"]
    assert cell["ok"] is True and cell["check"] == \
        "_dry_telemetry_overhead"
    assert cell["records"] > 0
    assert cell["hist_count"] > 0


def test_dry_campaign_cell():
    res = run_dry("--cell", "campaign_amortization")
    cell = res["dry"]["campaign_amortization"]
    assert cell["ok"] is True and cell["check"] == "_dry_campaign"
    assert cell["packs"] == 2
    assert cell["verdicts_identical"] is True


def test_dry_rejects_unknown_cell():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench.py", "--dry", "--cell", "nope"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
