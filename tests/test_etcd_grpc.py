"""The native-gRPC real-etcd adapter, driven hermetically.

client/etcd_grpc.py speaks etcdserverpb/v3lockpb over a real grpc
channel; sut/grpc_gateway.py serves those frames from the simulated
MVCC store. Round-tripping the adapter against the gateway exercises
the exact frames a live etcd would see (proto field numbers, compare
targets, txn branches, bidi watch + keepalive streams, compaction
cancel framing) — the reference's actual wire protocol (jetcd,
client.clj:14-68) without needing an etcd binary. Mirrors
test_etcd_http.py so both live adapters carry the same guarantees.
"""

import pytest

grpc = pytest.importorskip("grpc")

from jepsen_etcd_tpu.runner.wall import WallLoop
from jepsen_etcd_tpu.runner.sim import set_current_loop, SECOND
from jepsen_etcd_tpu.client.etcd_grpc import GrpcEtcdClient
from jepsen_etcd_tpu.client import txn as t
from jepsen_etcd_tpu.sut.grpc_gateway import serve_grpc
from jepsen_etcd_tpu.sut.errors import SimError


@pytest.fixture()
def gateway():
    srv, state, port = serve_grpc()
    endpoint = f"http://127.0.0.1:{port}"
    yield endpoint, state
    srv.stop(0)


def run(coro):
    loop = WallLoop()
    set_current_loop(loop)
    try:
        return loop.run_coro(coro)
    finally:
        set_current_loop(None)
        loop.shutdown()


def test_kv_roundtrip(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        assert await c.get("k") is None
        r = await c.put("k", {"a": [1, 2]})
        assert r["prev-kv"] is None
        kv = await c.get("k")
        assert kv["value"] == {"a": [1, 2]}
        assert kv["version"] == 1
        r = await c.put("k", "v2")
        assert r["prev-kv"]["value"] == {"a": [1, 2]}
        kv = await c.get("k")
        assert kv["version"] == 2
        assert await c.revision() >= kv["mod-revision"]
        return True

    assert run(main())


def test_cas_and_txn_guards(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        await c.put("reg", 1)
        ok = await c.cas("reg", 1, 2)
        assert ok["succeeded"]
        bad = await c.cas("reg", 1, 3)
        assert not bad["succeeded"]
        kv = await c.get("reg")
        assert kv["value"] == 2 and kv["version"] == 2
        # version + mod-revision guards (the append workload's shapes)
        res = await c.txn([t.eq("reg", t.version(2))],
                          [t.get("reg"), t.put("reg", 5)],
                          [t.get("reg")])
        assert res["succeeded"]
        assert res["gets"][0]["value"] == 2
        res = await c.txn(
            [t.lt("reg", t.mod_revision(1))],
            [t.put("reg", 9)], [t.get("reg")])
        assert not res["succeeded"]
        assert res["gets"][0]["value"] == 5
        return True

    assert run(main())


def test_swap_retry_loop(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        for i in range(5):
            got = await c.swap("s", lambda v: (v or 0) + 1)
            assert got == i + 1
        return True

    assert run(main())


def test_lease_lock_cycle(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        lease = await c.lease_grant(2 * SECOND)
        assert await c.lease_keepalive_once(lease) > 0
        key = await c.acquire_lock("lk", lease)
        assert key.startswith("lk/")
        await c.release_lock(key)
        await c.lease_revoke(lease)
        with pytest.raises(SimError) as ei:
            await c.lease_keepalive_once(lease)
        assert ei.value.type == "lease-not-found"
        return True

    assert run(main())


def test_lease_revoke_deletes_attached_keys(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        lease = await c.lease_grant(2 * SECOND)
        key = await c.acquire_lock("held", lease)
        assert await c.get(key) is not None
        await c.lease_revoke(lease)
        assert await c.get(key) is None  # lock key went with the lease
        return True

    assert run(main())


def test_lease_grant_rounds_ttl_up(gateway):
    """A 2.9s lease must become TTL=3, not 2 (same contract as the
    HTTP adapter: truncation would expire leases earlier than the
    harness's lease math assumes)."""
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        lease = await c.lease_grant(int(2.9 * SECOND))
        return await c.lease_keepalive_once(lease)

    assert run(main()) == 3 * SECOND


def test_watch_stream(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        from jepsen_etcd_tpu.runner.sim import current_loop, sleep
        loop = current_loop()
        seen = []
        done = loop.future()

        def on_events(evs):
            seen.extend(evs)
            if len(seen) >= 3:
                done.set_result(True)

        def on_error(e):
            if not done.done:
                done.set_exception(e)

        w = c.watch("w", 1, on_events, on_error)
        await sleep(int(0.1 * SECOND))
        for i in range(3):
            await c.put("w", i)
        await done
        w.cancel()
        assert [e.kv["value"] for e in seen[:3]] == [0, 1, 2]
        revs = [e.revision for e in seen]
        assert revs == sorted(revs)
        return True

    assert run(main())


def test_watch_compaction_cancel_carries_compact_revision(gateway):
    """A watch below the compact horizon must come back as a compacted
    cancel CARRYING the server's compact_revision (real etcd's
    canceled WatchResponse framing) — same contract as the HTTP
    adapter."""
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        from jepsen_etcd_tpu.runner.sim import current_loop
        loop = current_loop()
        for i in range(6):
            await c.put("ck", i)
        await c.compact(5)
        done = loop.future()

        def on_events(evs):
            pass

        def on_error(e):
            if not done.done:
                done.set_result(e)

        w = c.watch("ck", 1, on_events, on_error)  # below the horizon
        err = await done
        w.cancel()
        assert isinstance(err, SimError) and err.type == "compacted", err
        assert getattr(err, "compact_revision", None) == 5, vars(err)
        return True

    assert run(main())


def test_status_members_maintenance(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        st = await c.status()
        assert st["leader"] and "sim-gateway" in st["version"]
        ms = await c.member_list()
        assert len(ms) == 1 and ms[0]["id"] == 1
        assert await c.member_id_of_node("gw0") == 1
        await c.put("x", 1)
        await c.put("x", 2)
        await c.compact(await c.revision())
        await c.defrag()
        assert await c.await_node_ready()
        return True

    assert run(main())


def test_error_classification(gateway):
    endpoint, _ = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        await c.put("e", 1)
        await c.compact(await c.revision())
        with pytest.raises(SimError) as ei:
            await c.compact(1)   # below the compact horizon
        assert ei.value.type == "compacted" and ei.value.definite
        return True

    assert run(main())


def test_connect_failure_is_indefinite():
    async def main():
        c = GrpcEtcdClient("http://127.0.0.1:1")  # nothing listens
        with pytest.raises(SimError) as ei:
            await c.get("k")
        assert ei.value.type == "unavailable"
        assert not ei.value.definite
        return True

    assert run(main())


def test_register_workload_ops_against_gateway(gateway):
    """The register client's exact op shapes (read / write-with-prev-kv
    / value-cas) round-trip the gRPC wire and produce a linearizable
    history per the checker."""
    endpoint, _ = gateway
    from jepsen_etcd_tpu.core.op import Op
    from jepsen_etcd_tpu.core.history import History
    from jepsen_etcd_tpu.checkers import check_history
    from jepsen_etcd_tpu.models import VersionedRegister

    async def main():
        c = GrpcEtcdClient(endpoint)
        ops = []

        def rec(i, f, v):
            ops.append(Op(type="invoke", process=0, f=f,
                          value=[None, None if f == "read" else v]))
            ops.append(Op(type="ok", process=0, f=f, value=i))

        r = await c.put("r0", 3)
        prev = r.get("prev-kv")
        rec([(prev["version"] if prev else 0) + 1, 3], "write", 3)
        kv = await c.get("r0")
        rec([kv["version"], kv["value"]], "read", None)
        res = await c.cas("r0", 3, 4)
        assert res["succeeded"]
        ver = res["puts"][0]["prev-kv"]["version"] + 1
        rec([ver, [3, 4]], "cas", [3, 4])
        kv = await c.get("r0")
        rec([kv["version"], kv["value"]], "read", None)
        return History(ops)

    h = run(main())
    out = check_history(VersionedRegister(), h)
    assert out["valid?"] is True, out


def test_wire_interop_with_http_gateway_semantics(gateway):
    """The gRPC and HTTP adapters must produce identical kv dicts for
    identical operations — histories (and therefore checker verdicts)
    are client-type independent."""
    endpoint, state = gateway

    async def main():
        c = GrpcEtcdClient(endpoint)
        await c.put("same", {"x": 1})
        return await c.get("same")

    kv = run(main())
    assert kv["value"] == {"x": 1}
    assert set(kv) == {"key", "value", "version", "create-revision",
                       "mod-revision", "lease"}
    # the store itself saw the json-codec bytes (jepsen.codec contract)
    with state.lock:
        raw = state.store.range_interval("same", None)[0]
    assert raw["value"] == {"x": 1}
