"""Auxiliary-subsystem tests: --debug provenance + forensics, network
trace recorder (--tcpdump), serve, task-leak check, lazyfs checkpoint,
clock plot rendering, member-id surface (VERDICT r1 items 6-10 +
missing #8 + weak #5)."""

import json
import os
import threading
import urllib.request

import pytest

from jepsen_etcd_tpu.compose import etcd_test
from jepsen_etcd_tpu.runner.test_runner import run_test, check_task_leaks
from jepsen_etcd_tpu import forensics
from jepsen_etcd_tpu.core.op import Op


def run(tmp_path, **opts):
    base = {"time_limit": 10, "rate": 50, "store_base": str(tmp_path),
            "seed": 4}
    base.update(opts)
    return run_test(etcd_test(base))


# ---- debug provenance + forensics -----------------------------------------

def test_debug_provenance_wr(tmp_path):
    out = run(tmp_path, workload="wr", debug=True)
    assert out["results"]["workload"]["valid?"] is True
    oks = [op for op in out["history"]
           if op.get("type") == "ok" and op.get("f") == "txn"]
    assert oks, "no committed txns"
    # every committed txn carries raw responses for forensics
    assert all(isinstance(op.get("debug"), dict)
               and "txn-res" in op["debug"] for op in oks)
    # checker-visible read values are unwrapped (plain ints), but the
    # raw responses contain the provenance wrapper with this run's dir
    dirs = forensics.txn_dirs(out["history"])
    expected = (os.path.basename(os.path.dirname(out["dir"])) + "/"
                + os.path.basename(out["dir"]))
    assert dirs <= {expected}
    assert dirs, "no provenance-wrapped values ever read back"
    # revision maps extract, and a healthy run has no duplicates
    revs = forensics.wr_ops_revisions(oks)
    assert revs and all(r["key"] is not None and r["mod-revision"] is not None
                        for r in revs)
    assert forensics.duplicate_revisions(oks) == {}


def test_debug_provenance_append(tmp_path):
    out = run(tmp_path, workload="append", debug=True)
    assert out["results"]["workload"]["valid?"] is True
    oks = [op for op in out["history"]
           if op.get("type") == "ok" and op.get("f") == "txn"]
    assert oks
    assert all("read-res" in op["debug"] and "txn-res" in op["debug"]
               for op in oks if op.get("debug"))
    # reads stitched into txn values are decoded lists, not wrappers
    for op in oks:
        for f, k, v in op["value"]:
            if f == "r" and v is not None:
                assert isinstance(v, list), (f, k, v)


def test_forensics_on_saved_store(tmp_path):
    out = run(tmp_path, workload="wr", debug=True)
    runs = forensics.all_runs(str(tmp_path))
    assert out["dir"] in runs
    h = forensics.load_history(out["dir"])
    assert forensics.txn_dirs(h) == forensics.txn_dirs(out["history"])
    per_run = forensics.all_txn_dirs(str(tmp_path))
    assert out["dir"] in per_run


def test_duplicate_revisions_detects():
    # two reads observing the same (key, value) at different
    # mod-revisions — the anomaly the reference hunted (etcd.clj:337-346)
    def dbg_read(kv):
        return {"txn-res": {"results": [("get", kv)]}}

    ops = [
        Op(type="ok", f="txn", index=1, value=[["r", "x", [1]]],
           debug=dbg_read({"key": "x", "value": [1], "mod-revision": 5})),
        Op(type="ok", f="txn", index=2, value=[["r", "x", [1]]],
           debug=dbg_read({"key": "x", "value": [1], "mod-revision": 9})),
    ]
    dups = forensics.duplicate_revisions(ops)
    assert len(dups) == 1
    (key, _val), rms = next(iter(dups.items()))
    assert key == "x" and {r["mod-revision"] for r in rms} == {5, 9}
    assert forensics.ops_involving("x", ops) == ops


# ---- network trace recorder ------------------------------------------------

def test_trace_recorder(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["partition"],
              tcpdump=True, time_limit=20, seed=3, nemesis_interval=3)
    assert any(op.get("f") == "start-partition"
               for op in out["history"]), "seed produced no partition"
    trace_path = os.path.join(out["dir"], "trace.jsonl")
    assert os.path.exists(trace_path)
    events = [json.loads(l) for l in open(trace_path) if l.strip()]
    counts = {}
    for e in events:
        counts[e["kind"]] = counts.get(e["kind"], 0) + 1
    # replication heartbeats dominate; client rpcs and vote traffic exist
    assert counts.get("append", 0) > 100
    assert counts.get("client-rpc", 0) > 50
    assert counts.get("vote-req", 0) >= 4, counts
    # virtual timestamps are monotone
    ts = [e["t"] for e in events]
    assert all(a <= b for a, b in zip(ts, ts[1:]))
    # partitions drop messages
    assert any(e.get("delivered") is False for e in events)


def test_no_trace_without_flag(tmp_path):
    out = run(tmp_path, workload="register", time_limit=5)
    assert not os.path.exists(os.path.join(out["dir"], "trace.jsonl"))


# ---- serve -----------------------------------------------------------------

def test_serve_store(tmp_path):
    out = run(tmp_path, workload="register", time_limit=5)
    from jepsen_etcd_tpu.serve import make_server
    srv = make_server(str(tmp_path), port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        port = srv.server_address[1]
        idx = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        rel = os.path.relpath(out["dir"], str(tmp_path))
        assert rel in idx and "valid?" in idx
        # run report page: params, per-checker verdicts, artifacts
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{rel}/").read().decode()
        assert "Parameters" in page and "Checkers" in page
        assert "results.json" in page and "workload" in page
        # raw artifacts still served
        res = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{rel}/results.json")
        assert res.status == 200
        assert json.load(res).get("valid?") is True
        # ?files bypasses the report page for the raw listing
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{rel}/?files").read().decode()
        assert "history.jsonl" in raw
    finally:
        srv.shutdown()
        srv.server_close()


# ---- task-leak check -------------------------------------------------------

def test_task_leak_check_raises():
    from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop
    from jepsen_etcd_tpu.sut.errors import SimError
    loop = SimLoop(seed=0)
    set_current_loop(loop)
    try:
        async def stuck():
            await loop.future()  # never resolves

        loop.spawn(stuck(), name="rpc-n1")
        with pytest.raises(SimError) as ei:
            check_task_leaks(loop)
        assert ei.value.type == "task-leak"
        assert "rpc-n1" in str(ei.value)
    finally:
        set_current_loop(None)


def test_runs_pass_leak_check(tmp_path):
    # the check runs inside every run_test; lock workloads spawn
    # keepalive pumps — they must all drain
    out = run(tmp_path, workload="lock", time_limit=10)
    assert out["history"] is not None


# ---- lazyfs checkpoint -----------------------------------------------------

def test_lazyfs_checkpoint_pins_setup_state():
    from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop, sleep
    from jepsen_etcd_tpu.sut import Cluster, ClusterConfig, Txn
    from jepsen_etcd_tpu.sut.cluster import MS
    loop = SimLoop(seed=2)
    set_current_loop(loop)
    try:
        cluster = Cluster(loop, ["n1", "n2", "n3"],
                          ClusterConfig(unsafe_no_fsync=True, lazyfs=True))
        cluster.launch()

        async def main():
            while not any(n.role == "leader"
                          for n in cluster.nodes.values()):
                await sleep(100 * MS)
            await cluster.kv_txn(
                "n1", Txn((), (("put", "pinned", 1, 0),), ()))
            await sleep(500 * MS)
            for n in cluster.nodes:
                cluster.checkpoint_node(n)   # lazyfs checkpoint!
            await cluster.kv_txn(
                "n1", Txn((), (("put", "after", 2, 0),), ()))
            await sleep(200 * MS)
            # kill ALL nodes losing unfsynced writes; restart
            for n in list(cluster.nodes):
                cluster.kill_node(n, lose_unfsynced=True)
            for n in list(cluster.nodes):
                cluster.start_node(n)
            while not any(n.role == "leader"
                          for n in cluster.nodes.values()):
                await sleep(100 * MS)
            out = await cluster.kv_read("n1", "pinned")
            # the checkpointed write survives total crash; the
            # post-checkpoint write may legitimately be lost
            assert out["kv"] is not None and out["kv"]["value"] == 1

        loop.run_coro(main())
        cluster.shutdown()
    finally:
        set_current_loop(None)


# ---- clock plot ------------------------------------------------------------

def test_clock_plot_renders(tmp_path):
    out = run(tmp_path, workload="register", nemesis=["clock"],
              time_limit=15)
    clock = out["results"].get("clock", {})
    assert clock.get("valid?") is True
    if clock.get("points"):
        assert clock.get("plots") == ["clock.png"], clock.get("plot-error")
        assert os.path.exists(os.path.join(out["dir"], "clock.png"))


# ---- member ids ------------------------------------------------------------

def test_member_id_surface():
    from jepsen_etcd_tpu.runner.sim import SimLoop, set_current_loop, sleep
    from jepsen_etcd_tpu.sut import Cluster
    from jepsen_etcd_tpu.sut.cluster import MS, member_id
    from jepsen_etcd_tpu.client import DirectClient
    loop = SimLoop(seed=1)
    set_current_loop(loop)
    try:
        cluster = Cluster(loop, ["n1", "n2", "n3"])
        cluster.launch()

        async def main():
            while not any(n.role == "leader"
                          for n in cluster.nodes.values()):
                await sleep(100 * MS)
            c = DirectClient(cluster, "n1")
            ms = await c.member_list()
            assert {m["name"] for m in ms} == {"n1", "n2", "n3"}
            ids = {m["id"] for m in ms}
            assert len(ids) == 3 and all(isinstance(i, int) for i in ids)
            mid = await c.member_id_of_node("n2")
            assert mid == member_id("n2")
            assert await c.node_of_member_id(mid) == "n2"
            await c.remove_member_by_id(mid)
            await sleep(2000 * MS)
            ms2 = await c.member_list()
            assert {m["name"] for m in ms2} == {"n1", "n3"}

        loop.run_coro(main())
        cluster.shutdown()
    finally:
        set_current_loop(None)
